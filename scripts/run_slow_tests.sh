#!/usr/bin/env bash
# The slow tier: multi-OS-process elastic jobs (SIGKILL recovery, sharded
# checkpointing, eval interleave) and compile-heavy model tests that the
# default `pytest tests/` run skips (pyproject addopts: -m 'not slow').
# Run this before cutting a release or after touching the elastic plane:
#
#   scripts/run_slow_tests.sh            # the whole slow tier
#   scripts/run_slow_tests.sh -k kill    # just the kill-recovery rungs
#
# Wall-clock: ~6-10 min on an 8-core host (worker subprocesses run over
# gloo CPU collectives; no TPU needed). Run it on an otherwise idle
# host: the elastic rungs spawn real worker processes with liveness
# windows, and heavy concurrent load (e.g. another pytest run) can push
# them past their progress deadlines.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m pytest tests/ -m slow --override-ini="addopts=" -q "$@"
