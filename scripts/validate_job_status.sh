#!/usr/bin/env bash
# Poll a cluster job until its pods reach Succeeded (parity: reference
# scripts/validate_job_status.sh — reads the master pod's `status` label
# when TensorBoard keeps the pod alive).
set -euo pipefail

JOB_NAME=${1:?usage: validate_job_status.sh <job_name> [timeout_s]}
TIMEOUT=${2:-600}
NS=${NAMESPACE:-default}
MASTER="elasticdl-${JOB_NAME}-master"

for ((t = 0; t < TIMEOUT; t += 10)); do
    phase=$(kubectl -n "$NS" get pod "$MASTER" \
        -o jsonpath='{.status.phase}' 2>/dev/null || echo Missing)
    label=$(kubectl -n "$NS" get pod "$MASTER" \
        -o jsonpath='{.metadata.labels.status}' 2>/dev/null || true)
    echo "t=${t}s master phase=${phase} status-label=${label}"
    if [[ "$phase" == "Succeeded" || "$label" == "Finished" ]]; then
        echo "job ${JOB_NAME}: OK"
        exit 0
    fi
    if [[ "$phase" == "Failed" ]]; then
        kubectl -n "$NS" logs "$MASTER" --tail 50 || true
        exit 1
    fi
    sleep 10
done
echo "job ${JOB_NAME}: timeout" >&2
exit 1
