#!/usr/bin/env bash
# E2E CI job: train + evaluate + predict through the real CLI in local
# mode (parity: reference scripts/client_test.sh, which submits the same
# three jobs to minikube; local mode exercises the identical master/
# worker/dispatcher paths without a cluster).
set -euo pipefail

JOB_TYPE=${1:-train}
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

python -m elasticdl_tpu.data.recordio_gen.image_label \
    --output_dir "$WORK/data" --records_per_shard 128 \
    --dataset synthetic-mnist >/dev/null

case "$JOB_TYPE" in
train)
    python -m elasticdl_tpu.cli train \
        --job_name test-train \
        --model_zoo model_zoo \
        --model_def mnist_subclass.mnist_subclass.CustomModel \
        --minibatch_size 64 \
        --num_epochs 1 \
        --num_workers 2 \
        --use_async true \
        --training_data "$WORK/data" \
        --checkpoint_steps 10 --checkpoint_dir "$WORK/ckpt" \
        --output "$WORK/export"
    test -n "$(ls "$WORK"/export/*/model.chkpt)" || exit 1
    ;;
evaluate)
    python -m elasticdl_tpu.cli train \
        --job_name seed --model_zoo model_zoo \
        --model_def mnist_subclass.mnist_subclass.CustomModel \
        --minibatch_size 64 --num_epochs 1 --use_async true \
        --training_data "$WORK/data" \
        --checkpoint_steps 10 --checkpoint_dir "$WORK/ckpt"
    CKPT=$(ls "$WORK"/ckpt/model_v*.chkpt | tail -1)
    python -m elasticdl_tpu.cli evaluate \
        --job_name test-eval --model_zoo model_zoo \
        --model_def mnist_subclass.mnist_subclass.CustomModel \
        --minibatch_size 64 \
        --validation_data "$WORK/data" \
        --checkpoint_filename_for_init "$CKPT"
    ;;
predict)
    python -m elasticdl_tpu.cli train \
        --job_name seed --model_zoo model_zoo \
        --model_def mnist_subclass.mnist_subclass.CustomModel \
        --minibatch_size 64 --num_epochs 1 --use_async true \
        --training_data "$WORK/data" \
        --checkpoint_steps 10 --checkpoint_dir "$WORK/ckpt"
    CKPT=$(ls "$WORK"/ckpt/model_v*.chkpt | tail -1)
    python -m elasticdl_tpu.cli predict \
        --job_name test-predict --model_zoo model_zoo \
        --model_def mnist_subclass.mnist_subclass.CustomModel \
        --minibatch_size 64 \
        --prediction_data "$WORK/data" \
        --checkpoint_filename_for_init "$CKPT"
    ;;
*)
    echo "unknown job type $JOB_TYPE" >&2
    exit 2
    ;;
esac
echo "client_test $JOB_TYPE: OK"
