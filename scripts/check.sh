#!/usr/bin/env bash
# One correctness gate for the threaded data plane
# (docs/static_analysis.md):
#
#   1. edlint — the whole-program AST analyzer (R1-R11: concurrency,
#      jit-purity, cross-file blocking chains, the R8 lockset race
#      detector, R9 RPC retry-safety, R10 copy-on-wire, R11 lock-order
#      deadlock detection over the whole-program lock graph) with the
#      stale-ratchet check on
#      (allowlists may only shrink). The pass runs under a hard <30s
#      wall-clock budget — the mtime-keyed AST cache keeps warm runs
#      far below it — and emits --json; on failure the gate prints a
#      compact per-rule summary instead of the full report.
#   2. the data-plane suites under EDL_LOCKTRACE=1 — every
#      threading.Lock/RLock our code takes joins the runtime lock-order
#      sanitizer (ABBA raises deterministically instead of deadlocking)
#      and every test asserts no non-daemon thread leaks out. Each
#      traced suite also EXPORTS its witnessed acquisition-edge graph.
#   3. the static<->dynamic cross-check — every edge the sanitizer
#      witnessed at runtime must appear in R11's static lock graph
#      (a missing edge means the interprocedural summaries are
#      unsound: fail loudly, do not ratchet).
#
# Run from anywhere: ./scripts/check.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== edlint whole-program (R1-R11 + stale-ratchet check, 30s budget) =="
EDLINT_JSON="${TMPDIR:-/tmp}/edlint_gate.$$.json"
LOCK_EDGES="${TMPDIR:-/tmp}/edlint_gate.$$.edges.jsonl"
trap 'rm -f "$EDLINT_JSON" "$LOCK_EDGES"' EXIT
rc=0
timeout -k 5 30 python -m elasticdl_tpu.tools.edlint --stale --json \
    > "$EDLINT_JSON" || rc=$?
# only timeout(1)'s own kill codes are budget overruns: 124 (TERM) and
# 137 (KILL after -k). 125/126/127 mean timeout or python itself broke.
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "edlint gate: BUDGET EXCEEDED (rc=$rc; the whole-program pass"
    echo "must finish in <30s on the full tree — profile the analyzer"
    echo "or check for a cold cache + pathological module)"
    exit "$rc"
fi
if [ "$rc" -ne 0 ]; then
    EDLINT_JSON="$EDLINT_JSON" python - <<'PY'
import json
import os
import sys
from collections import Counter

try:
    with open(os.environ["EDLINT_JSON"]) as f:
        doc = json.load(f)
except (OSError, ValueError):
    # edlint crashed before emitting JSON — the traceback on stderr
    # above is the real failure, don't bury it under a JSONDecodeError
    print("edlint gate FAILED: no JSON output (analyzer crashed; "
          "see the traceback above)")
    sys.exit(0)
violations = [
    f for f in doc["findings"] if f["ratchet_state"] == "violation"
]
per_rule = Counter(f["rule"] for f in violations)
print(
    "edlint gate FAILED: %d violation(s) [%s], %d stale entr(ies), "
    "%d unparseable"
    % (
        len(violations),
        " ".join("%s:%d" % rf for rf in sorted(per_rule.items())),
        len(doc["stale"]),
        len(doc["broken"]),
    )
)
for f in violations[:10]:
    print("  %s:%d [%s] %s" % (f["file"], f["line"], f["rule"],
                               f["message"][:100]))
if len(violations) > 10:
    print("  ... %d more (python -m elasticdl_tpu.tools.edlint)"
          % (len(violations) - 10))
for s in doc["stale"]:
    print("  stale ratchet %s %s: budget %d, used %d — shrink it"
          % (s["rule"], s["file"], s["budget"], s["used"]))
for b in doc["broken"]:
    print("  unparseable %s: %s" % (b["file"], b["error"]))
PY
    exit "$rc"
fi
EDLINT_JSON="$EDLINT_JSON" python - <<'PY'
import json
import os

with open(os.environ["EDLINT_JSON"]) as f:
    doc = json.load(f)
lg = doc.get("lock_graph") or {}
print("   lock graph: %d lock(s), %d edge(s), %d cycle(s)"
      % (lg.get("nodes", 0), lg.get("edges", 0), lg.get("cycles", 0)))
PY

echo "== data-plane suites under the lock-order sanitizer =="
JAX_PLATFORMS=cpu EDL_LOCKTRACE=1 EDL_LOCKTRACE_EXPORT="$LOCK_EDGES" \
    python -m pytest \
    tests/test_input_pipeline.py \
    tests/test_ps_overlap.py \
    tests/test_async_concurrency.py \
    tests/test_elastic_pipeline.py \
    tests/test_compile_plane.py \
    tests/test_telemetry.py \
    tests/test_tracing.py \
    tests/test_locktrace.py \
    tests/test_edlint.py \
    tests/test_wire.py \
    tests/test_dense_sharding.py \
    tests/test_comm_plane.py \
    tests/test_ps_snapshot.py \
    tests/test_ps_device_parity.py \
    tests/test_tiered_store.py \
    tests/test_chaos.py \
    tests/test_master_journal.py \
    tests/test_serving.py \
    tests/test_serving_batcher.py \
    tests/test_layout_solver.py \
    -q -m 'not slow' -p no:cacheprovider "$@"

echo "== static<->dynamic lock-graph cross-check =="
if [ -s "$LOCK_EDGES" ]; then
    # warm cache from gate 1: well under the same 30s budget
    timeout -k 5 30 python -m elasticdl_tpu.tools.edlint \
        --lock-coverage "$LOCK_EDGES"
else
    echo "cross-check SKIPPED: the traced suites exported no edges" >&2
    echo "(EDL_LOCKTRACE_EXPORT produced an empty file — the conftest" >&2
    echo "export hook or the sanitizer install is broken)" >&2
    exit 1
fi

echo "check.sh: all gates green"
