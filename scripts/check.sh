#!/usr/bin/env bash
# One correctness gate for the threaded data plane
# (docs/static_analysis.md):
#
#   1. edlint — the AST concurrency/jit-purity analyzer over the whole
#      tree, all seven rules, stale-ratchet check on (allowlists may
#      only shrink);
#   2. the data-plane suites under EDL_LOCKTRACE=1 — every
#      threading.Lock/RLock our code takes joins the runtime lock-order
#      sanitizer (ABBA raises deterministically instead of deadlocking)
#      and every test asserts no non-daemon thread leaks out.
#
# Run from anywhere: ./scripts/check.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== edlint (R1-R7 + stale-ratchet check) =="
python -m elasticdl_tpu.tools.edlint --stale

echo "== data-plane suites under the lock-order sanitizer =="
JAX_PLATFORMS=cpu EDL_LOCKTRACE=1 python -m pytest \
    tests/test_input_pipeline.py \
    tests/test_ps_overlap.py \
    tests/test_async_concurrency.py \
    tests/test_elastic_pipeline.py \
    tests/test_compile_plane.py \
    tests/test_telemetry.py \
    tests/test_locktrace.py \
    tests/test_edlint.py \
    -q -m 'not slow' -p no:cacheprovider "$@"

echo "check.sh: all gates green"
