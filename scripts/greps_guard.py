#!/usr/bin/env python
"""(retired to a shim) Source-pattern guard for the r5 wedge classes.

The three regex rules that lived here — unescapable ``jax.devices()``
probes, unbounded blocking queue puts, and data-plane blocking queue
gets — are now REAL AST rules R1–R3 of the edlint analyzer
(``elasticdl_tpu/tools/edlint``, docs/static_analysis.md), which scopes
them to actual call-sites and actual ``queue.Queue`` receivers instead
of line patterns. The allowlists migrated, with their reasons, into
``elasticdl_tpu/tools/edlint/ratchet.py`` — two entries (odps_io put,
task_data_service put) dropped outright because the AST pass can prove
those queues are constructed unbounded.

This shim keeps the historical entry point (and tests/test_greps_guard)
working: it delegates to edlint restricted to R1–R3 with the same exit
contract (0 clean, 1 with a per-violation report).

Run: ``python scripts/greps_guard.py [--root REPO_ROOT]``.
"""

import os
import sys


def main(argv=None):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from elasticdl_tpu.tools.edlint.core import main as edlint_main

    args = list(sys.argv[1:] if argv is None else argv)
    return edlint_main(["--rules", "R1,R2,R3"] + args)


if __name__ == "__main__":
    sys.exit(main())
