#!/usr/bin/env python
"""Source-pattern guard for the two wedge classes VERDICT r5 root-caused.

1. ``jax.devices()`` outside ``escapable_call``: probing the device
   transport in-process with no timeout turns one wedged transport into
   a hung driver (the r5 grading outage). Every call site must either
   go through ``common/escapable.escapable_call`` (which the pattern
   does not match — it passes ``jax.devices`` uncalled) or be an
   allowlisted in-mesh site that only runs after the backend is
   established.

2. Unbounded blocking ``queue.put``: a producer putting into a bounded
   queue with no timeout+cancel loop blocks forever once its consumer
   is abandoned (the prefetch leak fixed in data/dataset.py). Every
   ``.put(`` on a queue must carry ``timeout=`` inside a cancel loop,
   be ``put_nowait``, or be an allowlisted put into an UNBOUNDED queue
   (which never blocks).

3. Unbounded blocking ``queue.get`` in the DATA PLANE (data/ and the
   task data service): a consumer getting with no timeout and no
   sentinel discipline blocks forever once its producer dies or the
   round is abandoned — the input-pipeline twin of rule 2
   (docs/input_pipeline.md). Every queue-ish ``.get(`` there must carry
   ``timeout=`` inside a cancel loop, be ``get_nowait``, or be an
   allowlisted get whose producer is guaranteed to deliver a terminal
   sentinel/exception (the prefetch _END protocol).

The allowlists are ratchets: per-file maximum occurrence counts. New
code that trips a rule must adopt the safe pattern or consciously
extend the allowlist here, with a reason, in the same review.

Run: ``python scripts/greps_guard.py [--root REPO_ROOT]``; exit 0 on
clean, 1 with a per-violation report otherwise. Wired into tier-1 via
tests/test_greps_guard.py.
"""

import argparse
import os
import re
import sys

# file (repo-relative, posix) -> max allowed occurrences, with why.
ALLOWED_DEVICES = {
    # in-mesh sites: run strictly after establish()/backend init, where
    # a wedge would already have surfaced through the escapable probe
    "elasticdl_tpu/parallel/elastic.py": 1,
    "elasticdl_tpu/parallel/mesh.py": 1,
    "elasticdl_tpu/worker/allreduce_worker.py": 1,
    # post-probe sites: __graft_entry__ calls these only after the
    # escapable_call device probe has already verified the transport
    "__graft_entry__.py": 2,
    # bench device sections run in subprocesses under section timeouts
    "bench.py": 3,
}

ALLOWED_PUTS = {
    # unbounded queue.Queue(): put never blocks
    "elasticdl_tpu/common/async_checkpoint.py": 2,
    "elasticdl_tpu/data/odps_io.py": 1,
    # Queue(maxsize=1) with exactly one put per producer thread
    "elasticdl_tpu/common/escapable.py": 2,
    # _TaskFetcher._offer: unbounded queue (depth bounded by the slot
    # semaphore the consumer releases), put under the offer lock so no
    # item can land after shutdown's final drain
    "elasticdl_tpu/worker/task_data_service.py": 1,
}

# data-plane files rule 3 applies to
GET_SCOPE_PREFIXES = ("elasticdl_tpu/data/",)
GET_SCOPE_FILES = ("elasticdl_tpu/worker/task_data_service.py",)

ALLOWED_GETS = {
    # prefetch's consumer get: the producer ALWAYS delivers a terminal
    # _END or exception sentinel through put_or_cancel, so the get
    # cannot outlive its producer (two sites: plain + stats-timed)
    "elasticdl_tpu/data/dataset.py": 2,
}

DEVICES_RE = re.compile(r"\b_?jax\.devices\(\)")
PUT_RE = re.compile(r"(?:\b(?P<recv>[A-Za-z_][A-Za-z0-9_]*))?\.put\(")
GET_RE = re.compile(r"\b(?P<recv>[A-Za-z_][A-Za-z0-9_]*)\.get\(")


def _queue_ish(recv):
    """Receiver names that read as a queue (not a dict/cache .get)."""
    low = recv.lower()
    return low == "q" or low.endswith("_q") or "queue" in low


def iter_source_files(root):
    yield from (
        os.path.join(root, name)
        for name in ("__graft_entry__.py", "bench.py")
        if os.path.exists(os.path.join(root, name))
    )
    pkg = os.path.join(root, "elasticdl_tpu")
    for dirpath, _, names in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def scan_file(path, root):
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    devices_hits = []
    put_hits = []
    get_hits = []
    in_get_scope = rel in GET_SCOPE_FILES or any(
        rel.startswith(p) for p in GET_SCOPE_PREFIXES
    )
    for i, line in enumerate(lines):
        m = DEVICES_RE.search(line)
        if (
            m
            and not line.lstrip().startswith("#")
            # prose mentions in docstrings/comments quote the call in
            # backticks; only bare code occurrences count
            and not line[: m.start()].endswith("`")
        ):
            devices_hits.append((rel, i + 1, line.strip()))
        for m in PUT_RE.finditer(line):
            recv = m.group("recv") or ""
            if "cache" in recv.lower():
                continue  # HotRowCache.put and kin: not a queue
            # the call may wrap: look at this line plus the next two
            # for the bounding timeout
            window = " ".join(lines[i : i + 3])
            if "timeout=" in window:
                continue
            put_hits.append((rel, i + 1, line.strip()))
        if in_get_scope:
            for m in GET_RE.finditer(line):
                if not _queue_ish(m.group("recv")):
                    continue  # dict/kwargs/cache .get, not a queue
                window = " ".join(lines[i : i + 3])
                if "timeout=" in window:
                    continue
                get_hits.append((rel, i + 1, line.strip()))
    return devices_hits, put_hits, get_hits


def check(root):
    violations = []
    devices_counts = {}
    put_counts = {}
    get_counts = {}
    for path in iter_source_files(root):
        devices_hits, put_hits, get_hits = scan_file(path, root)
        for rel, lineno, text in devices_hits:
            devices_counts[rel] = devices_counts.get(rel, 0) + 1
            if devices_counts[rel] > ALLOWED_DEVICES.get(rel, 0):
                violations.append(
                    "%s:%d: jax.devices() outside escapable_call "
                    "(wedged-transport hang risk): %s"
                    % (rel, lineno, text)
                )
        for rel, lineno, text in put_hits:
            put_counts[rel] = put_counts.get(rel, 0) + 1
            if put_counts[rel] > ALLOWED_PUTS.get(rel, 0):
                violations.append(
                    "%s:%d: blocking queue put without timeout+cancel "
                    "(abandoned-consumer leak risk): %s"
                    % (rel, lineno, text)
                )
        for rel, lineno, text in get_hits:
            get_counts[rel] = get_counts.get(rel, 0) + 1
            if get_counts[rel] > ALLOWED_GETS.get(rel, 0):
                violations.append(
                    "%s:%d: data-plane blocking queue get without "
                    "timeout/sentinel discipline (dead-producer hang "
                    "risk): %s" % (rel, lineno, text)
                )
    return violations


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--root",
        default=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
        help="repo root to scan (default: this script's repo)",
    )
    args = parser.parse_args(argv)
    violations = check(args.root)
    if violations:
        print("greps_guard: %d violation(s)" % len(violations))
        for v in violations:
            print("  " + v)
        print(
            "Fix: route device probes through "
            "common/escapable.escapable_call; bound queue puts with "
            "timeout= in a cancel loop (see data/dataset.py "
            "put_or_cancel); bound data-plane queue gets with timeout= "
            "in a cancel loop (see task_data_service._TaskFetcher."
            "next_item) or a guaranteed terminal sentinel. Deliberate "
            "exceptions extend the allowlists in scripts/greps_guard.py "
            "with a reason."
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
