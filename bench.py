"""Benchmark harness: ResNet-50/ImageNet examples/sec/chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} as required
by the driver (BASELINE.md). The default mode measures the fused jitted
train step (forward + backward + SGD update, bfloat16 compute on the MXU,
params f32) on the locally visible accelerator with on-device synthetic
data, so the number is the compute-path ceiling the input pipeline must
keep fed.

Additional modes (BASELINE.md "honest bench" rows):

- ``--e2e``: feeds the step from a generated EDLR record file through the
  framework's reader + Dataset shim (decode, map, shuffle, batch,
  prefetch) — what a worker actually runs, so input-pipeline regressions
  show up here.
- ``--preemption``: runs the local elastic allreduce job (3 worker OS
  processes over gloo CPU collectives), kills one mid-job, and reports
  wall-clock vs the undisturbed run — the BASELINE.md "job wall-clock
  under worker preemption" metric.
- ``--profile DIR``: wraps the measured loop in a jax.profiler trace
  (elasticdl_tpu/utils/profiling.py).

``vs_baseline`` compares against the value recorded in BASELINE.json under
``published["resnet50_examples_per_sec_per_chip"]`` when present (the
reference publishes no numbers — BASELINE.md; this repo's own first
measurement seeds the ratchet), else 1.0.
"""

import json
import os
import sys
import time

import numpy as np


def bench_e2e(quick=False):
    """Train-step throughput fed by the real input pipeline (EDLR file ->
    C++/Python reader -> Dataset shim -> host batches -> device)."""
    import tempfile

    import jax

    from elasticdl_tpu.data.data_reader import RecordIODataReader
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.task_dispatcher import Task
    from elasticdl_tpu.common.constants import Mode, TaskType
    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    batch = 16 if quick else 64
    image = 64 if quick else 224
    records = batch * (4 if quick else 12)

    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="edl_bench_")
    path = os.path.join(tmp, "bench.edlr")
    with RecordIOWriter(path) as w:
        for _ in range(records):
            w.write(
                encode_example(
                    {
                        "image": rng.integers(
                            255, size=(image, image, 3), dtype=np.int64
                        ).astype(np.uint8),
                        "label": np.array(
                            [rng.integers(1, 1001)], dtype=np.int64
                        ),
                    }
                )
            )

    reader = RecordIODataReader(data_dir=tmp)

    def one_pass():
        task = Task(path, 0, records, TaskType.TRAINING)
        ds = Dataset.from_generator(
            lambda: iter(reader.read_records(task))
        )
        ds = zoo.dataset_fn(ds, Mode.TRAINING, None)
        # device_prefetch last: batches double-buffer onto the chip so
        # the h2d transfer overlaps the previous step's compute
        return ds.batch(batch).prefetch(2).device_prefetch()

    model = zoo.custom_model()
    first = next(iter(one_pass()))
    variables = init_variables(
        model,
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda x: np.asarray(x)[:1], first[0]),
    )
    params, state = split_variables(variables)
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)
    key = jax.random.PRNGKey(1)

    # warm both the compile cache and the reader page cache
    ts, loss = step_fn(ts, first[0], first[1], key)
    float(loss)

    t0 = time.perf_counter()
    n_examples = 0
    epochs = 1 if quick else 2
    for _ in range(epochs):
        for features, labels in one_pass():
            # shape check must not force a device->host fetch
            n = jax.tree_util.tree_leaves(labels)[0].shape[0]
            if n != batch:
                continue  # static-shape step; tail batch skipped
            ts, loss = step_fn(ts, features, labels, key)
            n_examples += n
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)
    return n_examples / dt


def bench_preemption():
    """Wall-clock of the 3-process elastic allreduce job with one worker
    SIGKILLed mid-run, relative to the undisturbed run (CPU/gloo)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r); sys.path.insert(0, %r)\n"
        "from tests.test_elastic_allreduce import (\n"
        "    test_elastic_allreduce_survives_worker_kill,\n"
        "    test_elastic_allreduce_two_process_job,\n"
        ")\n"
        "import tempfile, time, pathlib\n"
        "t0 = time.time()\n"
        "test_elastic_allreduce_two_process_job(pathlib.Path(tempfile.mkdtemp()))\n"
        "clean = time.time() - t0\n"
        "t0 = time.time()\n"
        "test_elastic_allreduce_survives_worker_kill(pathlib.Path(tempfile.mkdtemp()))\n"
        "killed = time.time() - t0\n"
        "import json\n"
        "print('PREEMPTION ' + json.dumps({'clean_s': round(clean, 1),"
        " 'killed_s': round(killed, 1)}))\n"
    ) % (here, here)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=here,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("PREEMPTION "):
            return json.loads(line[len("PREEMPTION "):])
    raise RuntimeError(
        "preemption bench failed:\n" + proc.stdout[-2000:] + proc.stderr[-2000:]
    )


def main(argv=None):
    argv = argv or sys.argv[1:]
    quick = "--quick" in argv

    if "--preemption" in argv:
        res = bench_preemption()
        print(
            json.dumps(
                {
                    "metric": "elastic_job_wallclock_under_kill",
                    "value": res["killed_s"],
                    "unit": "seconds (vs %.1fs undisturbed 2-proc run)"
                    % res["clean_s"],
                    "vs_baseline": 1.0,
                }
            )
        )
        return 0

    if "--e2e" in argv:
        eps = bench_e2e(quick)
        print(
            json.dumps(
                {
                    "metric": "resnet50_e2e_examples_per_sec_per_chip",
                    "value": round(eps, 2),
                    "unit": "examples/sec/chip (EDLR file -> Dataset -> step)",
                    "vs_baseline": 1.0,
                }
            )
        )
        return 0

    import jax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    batch = 32 if quick else 128
    image = 64 if quick else 224
    steps = 3 if quick else 20

    model = zoo.custom_model()
    rng = np.random.default_rng(0)
    features = {
        "image": rng.random((batch, image, image, 3), dtype=np.float32)
    }
    labels = rng.integers(0, 1000, size=(batch, 1)).astype(np.int32)

    variables = init_variables(
        model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
    )
    params, state = split_variables(variables)
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)

    dev_features = jax.device_put(features)
    dev_labels = jax.device_put(labels)
    step_rng = jax.random.PRNGKey(1)

    # warmup/compile. Synchronize with a host scalar fetch, not
    # block_until_ready: some remote-execution transports (the axon dev
    # tunnel) return from block_until_ready before compute completes, and
    # only a device->host read forces full execution.
    for _ in range(2):
        ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
    float(loss)

    if "--profile" in argv:
        from elasticdl_tpu.utils.profiling import trace

        idx = argv.index("--profile")
        if idx + 1 >= len(argv) or argv[idx + 1].startswith("-"):
            print(
                json.dumps(
                    {"error": "--profile requires a directory argument"}
                )
            )
            return 2
        ctx = trace(argv[idx + 1])
    else:
        import contextlib

        ctx = contextlib.nullcontext()

    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
        final_loss = float(loss)
        dt = time.perf_counter() - t0
    if not np.isfinite(final_loss):
        print(json.dumps({"error": "non-finite loss in benchmark"}))
        return 1

    examples_per_sec = batch * steps / dt

    baseline = None
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
    )
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)["published"].get(
                "resnet50_examples_per_sec_per_chip"
            )
    except Exception:
        pass

    result = {
        "metric": "resnet50_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(examples_per_sec / baseline, 3)
        if baseline
        else 1.0,
    }
    print(json.dumps(result))

    if "--update-baseline" in argv and not quick:
        # persist the ratchet value bench reads back next run
        with open(baseline_path) as f:
            data = json.load(f)
        data.setdefault("published", {})[
            "resnet50_examples_per_sec_per_chip"
        ] = result["value"]
        with open(baseline_path, "w") as f:
            json.dump(data, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
