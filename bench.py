"""Benchmark harness: ResNet-50/ImageNet examples/sec/chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} as required
by the driver (BASELINE.md). Measures the fused jitted train step (forward
+ backward + SGD update, bfloat16 compute on the MXU, params f32) on the
locally visible accelerator with on-device synthetic data, so the number
is the compute-path ceiling the input pipeline must keep fed.

``vs_baseline`` compares against the value recorded in BASELINE.json under
``published["resnet50_examples_per_sec_per_chip"]`` when present (the
reference publishes no numbers — BASELINE.md; this repo's own first
measurement seeds the ratchet), else 1.0.
"""

import json
import os
import sys
import time

import numpy as np


def main(argv=None):
    argv = argv or sys.argv[1:]
    quick = "--quick" in argv

    import jax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    batch = 32 if quick else 128
    image = 64 if quick else 224
    steps = 3 if quick else 20

    model = zoo.custom_model()
    rng = np.random.default_rng(0)
    features = {
        "image": rng.random((batch, image, image, 3), dtype=np.float32)
    }
    labels = rng.integers(0, 1000, size=(batch, 1)).astype(np.int32)

    variables = init_variables(
        model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
    )
    params, state = split_variables(variables)
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)

    dev_features = jax.device_put(features)
    dev_labels = jax.device_put(labels)
    step_rng = jax.random.PRNGKey(1)

    # warmup/compile. Synchronize with a host scalar fetch, not
    # block_until_ready: some remote-execution transports (the axon dev
    # tunnel) return from block_until_ready before compute completes, and
    # only a device->host read forces full execution.
    for _ in range(2):
        ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    if not np.isfinite(final_loss):
        print(json.dumps({"error": "non-finite loss in benchmark"}))
        return 1

    examples_per_sec = batch * steps / dt

    baseline = None
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
    )
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)["published"].get(
                "resnet50_examples_per_sec_per_chip"
            )
    except Exception:
        pass

    result = {
        "metric": "resnet50_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(examples_per_sec / baseline, 3)
        if baseline
        else 1.0,
    }
    print(json.dumps(result))

    if "--update-baseline" in argv and not quick:
        # persist the ratchet value bench reads back next run
        with open(baseline_path) as f:
            data = json.load(f)
        data.setdefault("published", {})[
            "resnet50_examples_per_sec_per_chip"
        ] = result["value"]
        with open(baseline_path, "w") as f:
            json.dump(data, f, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
