"""Benchmark harness: every headline number the framework publishes.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} per metric
as required by the driver (BASELINE.md). The default mode runs the compact
ratcheted SUITE — ResNet-50 examples/s, 110M transformer tokens/s + MFU,
flash-attention speedup at L=2048, and the elastic preemption
killed/clean ratio — each line compared against its BASELINE.json ratchet,
so a regression in any headline surface fails loudly in the per-round
capture. ``--resnet`` (or ``--quick``) runs just the fused jitted
ResNet-50 train step (forward + backward + SGD update, bfloat16 compute on
the MXU, params f32) with on-device synthetic data — the compute-path
ceiling the input pipeline must keep fed.

Additional modes (BASELINE.md "measured baselines" rows):

- ``--transformer``: transformer_lm fused train step at a GPT-2-small-ish
  config — tokens/s/chip and **MFU**, with the Pallas flash-attention
  kernel on (default) or off (``--no-flash``). The round-2 flash claim
  ("no (L,L) materialized anywhere") gets its measured number here.
- ``--flash``: flash vs reference attention fwd+bwd microbench across
  sequence lengths (scan-measured, DCE-proof: grads fold into the scan
  carry so XLA cannot elide iterations). Reports the L=2048 speedup as
  the metric; per-L table goes to stderr.
- ``--embedding``: HBM embedding lookup forms (plain take vs gather+psum
  vs a2a routing) in rows/s at a realistic batch on the visible mesh.
  On one chip the collectives are degenerate (no ICI traffic) — the
  number is kernel/routing overhead; the multi-device form is exercised
  for correctness on the CPU mesh in tests.
- ``--a2a-dedup``: the sparse-comms fast path (dedup-before-comm a2a)
  vs naive per-occurrence routing on a power-law duplicated-ID batch —
  the recommendation-workload shape ``--embedding``'s uniform ids never
  measure (docs/sparse_fast_path.md). ``--ps`` likewise carries two
  extra arms on a power-law id file: the naive per-occurrence PS plane
  vs dedup + row-combined push + hot-row cache. Since the overlapped
  data plane (docs/dense_overlap.md) it also carries serial-vs-overlap
  arms (concurrent shard fan-out + double-buffered async push) and a
  slow-shard fan-out microbench whose wall must track the slowest
  shard, not the shard sum.
- ``--hybrid``: the hybrid comm plane (docs/embedding_planes.md) vs the
  PS-everything trainer on the same 2-process injected-RTT fleet —
  dense parameters local + the PS-plane table's pull overlapped behind
  the previous batch's compute, against every parameter round-tripping
  through the PS at its best known config. Gated >=1.3x, behind a
  bitwise lookup/gradient equivalence pre-pass. CPU-only; part of the
  default suite.
- ``--e2e``: feeds the step from a generated EDLR record file through the
  framework's reader + Dataset shim (decode, map, shuffle, batch,
  prefetch) — what a worker actually runs, so input-pipeline regressions
  show up here.
- ``--input``: serial vs pipelined worker input plane (task prefetch +
  parallel ordered decode + vectorized batch assembly + queued acks)
  through the REAL task data service, under injected ``get_task`` RTT
  and per-record read latency, with an identical-stream equivalence
  pre-pass (docs/input_pipeline.md). CPU-only; part of the default
  suite.
- ``--preemption``: runs the local elastic allreduce job (3 worker OS
  processes over gloo CPU collectives), kills one mid-job, and reports
  wall-clock vs the undisturbed run — the BASELINE.md "job wall-clock
  under worker preemption" metric.
- ``--profile DIR``: wraps the measured loop in a jax.profiler trace
  (elasticdl_tpu/utils/profiling.py).

``vs_baseline`` compares against the value recorded in BASELINE.json under
``published[<metric>]`` when present (the reference publishes no numbers —
BASELINE.md; this repo's own first measurement seeds the ratchet), else
1.0. ``--update-baseline`` persists the current value as the new ratchet.

Measurement discipline (see BASELINE.md round-2 profile): steps run under
a ``lax.scan`` inside one jit with iteration-dependent inputs, and every
timing section synchronizes with a device->host scalar fetch —
``block_until_ready`` returns early through the axon dev tunnel.
"""

import functools
import json
import os
import sys
import time

import numpy as np

# v5e bf16 peak per chip; override for other parts (v4: 275)
PEAK_TFLOPS = float(os.environ.get("EDL_PEAK_TFLOPS", "197"))


def _read_baseline(metric):
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
    )
    try:
        with open(path) as f:
            return json.load(f)["published"].get(metric)
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        # no baseline yet / malformed file (including a non-dict top
        # level): report without a ratchet
        return None


_EDLINT_STATE = []


def _edlint_regressed():
    """Violation count of the edlint concurrency gate (cached).

    A perf PR that trades a speedup for a lock-order or queue-
    discipline regression is not a win: speedup metrics are withheld
    while the tree is dirty (docs/static_analysis.md)."""
    if not _EDLINT_STATE:
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            if here not in sys.path:
                sys.path.insert(0, here)
            from elasticdl_tpu.tools.edlint.core import run as edlint_run

            violations, _, broken = edlint_run(here)
            _EDLINT_STATE.append(len(violations) + len(broken))
        except Exception as e:
            # analyzer import/scan failure must not silently unlock the
            # gate NOR block non-speedup reporting
            print(
                json.dumps(
                    {"metric": "edlint_gate", "error": str(e)[-200:]}
                )
            )
            _EDLINT_STATE.append(1)
    return _EDLINT_STATE[0]


def _emit(metric, value, unit, update=False, lower_is_better=False):
    """One driver JSON line. ``vs_baseline`` is uniformly
    higher-is-better: for a lower-is-better metric (preemption ratio)
    it is baseline/value, so >1 always reads as an improvement.

    Speedup metrics are gated on a clean edlint run: a perf number
    measured on top of a concurrency regression is withheld, with the
    reason in the error line."""
    if "speedup" in metric and _edlint_regressed():
        print(
            json.dumps(
                {
                    "metric": metric,
                    "error": "speedup withheld: edlint reports %d "
                    "violation(s) — fix them or ratchet with a reason "
                    "(python -m elasticdl_tpu.tools.edlint)"
                    % _edlint_regressed(),
                }
            )
        )
        return
    baseline = _read_baseline(metric)
    if baseline:
        ratio = baseline / value if lower_is_better else value / baseline
    else:
        ratio = 1.0
    print(
        json.dumps(
            {
                "metric": metric,
                "value": value,
                "unit": unit,
                "vs_baseline": round(ratio, 3),
            }
        )
    )
    if update:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
        )
        with open(path) as f:
            data = json.load(f)
        data.setdefault("published", {})[metric] = value
        with open(path, "w") as f:
            json.dump(data, f, indent=2)


def bench_transformer(quick=False, use_flash=True, large=False):
    """transformer_lm train-step tokens/s + MFU on the visible chip.

    Default: GPT-2-small-ish (110M: 12 layers, 12 heads x 64, d768,
    mlp 3072, vocab 32k; b16 L1024 — the measured-best batch). ``large``
    switches to a 730M config (24L, 16h x 96, d1536, mlp 6144; b4) whose
    bigger matmuls run at higher MFU (53%+ vs 43%). bf16 compute / f32
    params. Steps run under lax.scan with the token batch derived from
    the carry (rolled by the step index) so no iteration can be hoisted
    or elided; the carry is donated — beyond ~300M the adam state plus a
    second in-flight copy exceeds single-chip HBM without donation.
    """
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.transformer_lm import transformer_lm as zoo

    if quick or _on_cpu():
        # CPU backends always run the toy config (the 110M step is
        # minutes-per-step on CPU — the BENCH_r05 suite wedge class);
        # main() keeps the published metric name honest (_quick/_cpu)
        cfg = dict(
            vocab_size=512, num_layers=2, num_heads=4, head_dim=32,
            embed_dim=128, mlp_dim=512,
        )
        batch, seq, steps = 2, 256, 3
    elif large:
        cfg = dict(
            vocab_size=32768, num_layers=24, num_heads=16, head_dim=96,
            embed_dim=1536, mlp_dim=6144,
        )
        batch, seq, steps = 4, 1024, 6
    else:
        cfg = dict(
            vocab_size=32768, num_layers=12, num_heads=12, head_dim=64,
            embed_dim=768, mlp_dim=3072,
        )
        # b16 measured best on v5e (config sweep, BASELINE.md r3):
        # 42% MFU vs 37% at b8 and 38% at b32
        batch, seq, steps = 16, 1024, 10
    model = zoo.custom_model(dtype="bfloat16", use_flash=use_flash, **cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(
        0, cfg["vocab_size"], size=(batch, seq + 1), dtype=np.int32
    )
    features = {"tokens": tokens[:, :-1]}
    labels = tokens[:, 1:]

    variables = init_variables(
        model, jax.random.PRNGKey(0), {"tokens": features["tokens"][:1]}
    )
    params, state = split_variables(variables)
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
    )
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)
    dev_feat = jax.device_put(features)
    dev_lab = jax.device_put(labels)
    key = jax.random.PRNGKey(1)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(ts, feat, lab):
        def body(carry, i):
            ts, acc = carry
            # iteration-dependent tokens: roll by the step index so no
            # step's compute can be CSE'd or hoisted out of the scan
            f = {"tokens": jnp.roll(feat["tokens"], i, axis=1)}
            ts, loss = step_fn(ts, f, jnp.roll(lab, i, axis=1), key)
            return (ts, acc + loss), ()

        (ts, acc), _ = jax.lax.scan(
            body, (ts, jnp.float32(0.0)), jnp.arange(steps)
        )
        return ts, acc

    ts, acc = run(ts, dev_feat, dev_lab)
    float(acc)  # compile + warm; host fetch = real completion
    t0 = time.perf_counter()
    ts, acc = run(ts, dev_feat, dev_lab)
    final = float(acc)
    dt = time.perf_counter() - t0
    assert np.isfinite(final), "non-finite loss in transformer bench"

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    # model FLOPs: 6 * n_params per token (fwd+bwd weight matmuls; the
    # tied LM head is inside n_params) + causal attention
    # 3.5 * 2*b*l^2*h*d / 2 per layer (fwd QK^T+PV halved by causality;
    # x3.5 fwd+bwd with the flash backward's recompute)
    attn = (
        3.5
        * 2
        * batch
        * seq
        * seq
        * cfg["num_heads"]
        * cfg["head_dim"]
        / 2
        * cfg["num_layers"]
    )
    flops_per_step = 6.0 * n_params * tokens_per_step + attn
    mfu = flops_per_step * steps / dt / (PEAK_TFLOPS * 1e12)
    desc = "%dM-param LM, b%d L%d, bf16" % (
        n_params // 1_000_000,
        batch,
        seq,
    )
    print(
        "transformer_lm %s, flash=%s: %.0f tokens/s, MFU %.1f%%"
        % (desc, use_flash, tokens_per_sec, mfu * 100),
        file=sys.stderr,
    )
    return tokens_per_sec, mfu, desc


def _time_attention_grad(fn, b, l, h, d, iters, repeats=3):
    """Seconds per fwd+bwd of ``fn(q, k, v)`` (scan-measured, DCE-proof).

    The carry perturbs q AND consumes all three gradients: gq and gk/gv
    come from SEPARATE pallas_calls in the flash VJP, so a carry that
    only reads gq would let XLA dead-code-eliminate the dk/dv kernel and
    time a partial backward."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, l, h, d)), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def step(carry, i):
            gq, gk, gv = grad(q + carry * 1e-30, k, v)
            return (
                carry
                + gq.astype(jnp.float32).sum() * 1e-30
                + gk.astype(jnp.float32).sum() * 1e-30
                + gv.astype(jnp.float32).sum() * 1e-30
            ), ()

        c, _ = lax.scan(step, jnp.float32(0.0), jnp.arange(iters))
        return c

    float(run(q, k, v))  # compile+warm
    best = 1e9
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(run(q, k, v))
        best = min(best, time.perf_counter() - t0)
    return best / iters


def bench_flash(quick=False, lengths=None):
    """Flash vs reference attention fwd+bwd across L (scan, DCE-proof)."""
    from elasticdl_tpu.ops.flash_attention import flash_attention
    from elasticdl_tpu.parallel.ring_attention import reference_attention

    iters = 5 if quick else 50

    def one(fn, b, l, h, d):
        return _time_attention_grad(
            fn, b, l, h, d, iters, repeats=2 if quick else 3
        )

    b, h, d = 4, 8, 64
    if lengths is None:
        lengths = (512, 1024) if quick else (512, 1024, 2048, 4096)
    speedup_at = lengths[-1] if quick else 2048
    speedup = None
    for L in lengths:
        t_flash = one(lambda q, k, v: flash_attention(q, k, v, True), b, L, h, d)
        t_ref = one(
            lambda q, k, v: reference_attention(q, k, v, causal=True),
            b, L, h, d,
        )
        # causal fwd ~ 2*b*h*L^2*d / 2; fwd+bwd ~ x3.5 with recompute
        fl = 3.5 * 2 * b * h * L * L * d / 2
        print(
            "L=%5d: flash %7.2fms (%5.1f TF/s)  ref %7.2fms (%5.1f TF/s) "
            " speedup %.2fx"
            % (
                L,
                t_flash * 1e3,
                fl / t_flash / 1e12,
                t_ref * 1e3,
                fl / t_ref / 1e12,
                t_ref / t_flash,
            ),
            file=sys.stderr,
        )
        if L == speedup_at:
            speedup = t_ref / t_flash
    return speedup, speedup_at


def bench_longcontext(quick=False):
    """Flash attention fwd+bwd at long L — the lengths where an unfused
    attention cannot run at all (the (L, L) bf16 score tensor at L=16k+
    with b1 h8 exceeds single-chip HBM). Reports tokens/s/layer at the
    longest length that completes; the per-L table goes to stderr."""
    from elasticdl_tpu.ops.flash_attention import flash_attention
    from elasticdl_tpu.parallel.ring_attention import reference_attention

    iters = 3 if quick else 10
    h, d = 8, 64

    def one(fn, b, l):
        return _time_attention_grad(fn, b, l, h, d, iters, repeats=2)

    shapes = ((2, 4096), (1, 8192)) if quick else (
        (2, 8192), (1, 16384), (1, 32768), (1, 65536),
    )
    best = None
    for b, L in shapes:
        row = "b=%d L=%5d:" % (b, L)
        try:
            t = one(lambda q, k, v: flash_attention(q, k, v, True), b, L)
            tok_s = b * L / t
            best = (L, tok_s)
            row += " flash %8.1fms (%7.0f tok/s/layer)" % (t * 1e3, tok_s)
        except Exception as e:
            row += " flash FAIL(%s)" % type(e).__name__
        try:
            t = one(
                lambda q, k, v: reference_attention(q, k, v, causal=True),
                b, L,
            )
            row += "  ref %8.1fms" % (t * 1e3)
        except Exception as e:
            # expected from L=16k up: the (L,L) score tensor OOMs
            row += "  ref FAIL(%s)" % type(e).__name__
        print(row, file=sys.stderr, flush=True)
    return best


def bench_embedding(quick=False):
    """HBM embedding lookup forms in rows/s on the visible devices.

    Fwd+bwd through each lookup (the backward's routed scatter-add is
    half the story), scan-measured. Vocab 1M x 64 (sharded it is the
    deepfm_edl_embedding shape class), batch 8192 ids/step.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh

    from elasticdl_tpu.nn.hbm_embedding import (
        all_to_all_lookup,
        sharded_lookup,
    )

    vocab, dim = (4096, 16) if quick else (1 << 20, 64)
    n_ids = 512 if quick else 8192
    iters = 5 if quick else 30
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("data",))
    rng = np.random.default_rng(0)
    table = jnp.asarray(
        rng.standard_normal((vocab, dim)), jnp.float32
    )
    ids = jnp.asarray(
        rng.integers(0, vocab, size=(n_ids,)), jnp.int32
    )

    def timed(fn):
        def loss(t, i):
            return jnp.sum(fn(t, i).astype(jnp.float32) ** 2)

        grad = jax.grad(loss)

        @jax.jit
        def run(t, i0):
            def step(carry, k):
                g = grad(t + carry * 1e-30, (i0 + k) % vocab)
                return carry + g.sum() * 1e-30, ()

            c, _ = lax.scan(step, jnp.float32(0.0), jnp.arange(iters))
            return c

        float(run(table, ids))
        best = 1e9
        for _ in range(2 if quick else 3):
            t0 = time.perf_counter()
            float(run(table, ids))
            best = min(best, time.perf_counter() - t0)
        return n_ids * iters / best  # rows/s

    results = {
        "take": timed(lambda t, i: jnp.take(t, i, axis=0)),
        "psum": timed(lambda t, i: sharded_lookup(t, i, mesh, "data")),
        "a2a": timed(
            lambda t, i: all_to_all_lookup(
                t, i, mesh, "data", capacity=n_ids
            )
        ),
        "_desc": "%dK x %d table, %d ids/step" % (vocab // 1024, dim, n_ids),
    }
    for k, v in results.items():
        if not k.startswith("_"):
            print(
                "embedding %s: %.2fM rows/s (fwd+bwd)" % (k, v / 1e6),
                file=sys.stderr,
            )
    return results


def bench_a2a_dedup(quick=False):
    """Sparse-comms fast path on a power-law duplicated-ID batch: the
    dedup-before-comm a2a routing (batch-wide unique ids over the wire,
    per-occurrence rows restored by a local inverse-map gather, one
    combined gradient row per unique id on the way back) against the
    naive per-occurrence routing the pre-fast-path plane shipped.
    Recommendation batches repeat head ids many times (here: ids drawn
    zipf-style from a pool of batch/8 distinct ids, >= 8x average
    duplication), which the uniform-random ``--embedding`` section
    never measured. Fwd+bwd, scan-measured like bench_embedding; the
    naive arm needs capacity = batch (worst case per-occurrence), the
    dedup arm is correct at capacity = pool — an 8x smaller wire
    buffer in both directions."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh

    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    shrink = quick or _on_cpu()  # CPU: the 1M-row table grad is ~256MB/step
    vocab, dim = (4096, 16) if shrink else (1 << 20, 64)
    n_ids = 512 if shrink else 8192
    pool = n_ids // 8
    iters = 5 if shrink else 30
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("data",))
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)
    pool_ids = rng.permutation(vocab)[:pool]
    weights = 1.0 / np.arange(1, pool + 1) ** 1.1
    weights /= weights.sum()
    ids_np = rng.choice(pool_ids, size=(n_ids,), p=weights)
    dup_factor = n_ids / len(np.unique(ids_np))
    ids = jnp.asarray(ids_np, jnp.int32)

    def timed(fn):
        def loss(t, i):
            return jnp.sum(fn(t, i).astype(jnp.float32) ** 2)

        grad = jax.grad(loss)

        @jax.jit
        def run(t, i0):
            def step(carry, k):
                # shifting every id by k preserves the duplication
                # structure exactly while defeating cross-iteration CSE
                g = grad(t + carry * 1e-30, (i0 + k) % vocab)
                return carry + g.sum() * 1e-30, ()

            c, _ = lax.scan(step, jnp.float32(0.0), jnp.arange(iters))
            return c

        float(run(table, ids))
        best = 1e9
        for _ in range(2 if quick else 3):
            t0 = time.perf_counter()
            float(run(table, ids))
            best = min(best, time.perf_counter() - t0)
        return n_ids * iters / best  # rows/s (per-occurrence rows)

    naive = timed(
        lambda t, i: all_to_all_lookup(
            t, i, mesh, "data", capacity=n_ids, dedup=False
        )
    )
    dedup = timed(
        lambda t, i: all_to_all_lookup(
            t, i, mesh, "data", capacity=pool, dedup=True
        )
    )
    desc = "%dK x %d table, %d ids/step, %.1fx avg duplication" % (
        vocab // 1024,
        dim,
        n_ids,
        dup_factor,
    )
    print(
        "a2a-dedup (%s): naive %.2fM rows/s, dedup %.2fM rows/s "
        "(%.2fx)" % (desc, naive / 1e6, dedup / 1e6, dedup / naive),
        file=sys.stderr,
    )
    return {
        "naive": naive,
        "dedup": dedup,
        "dup_factor": dup_factor,
        "_desc": desc,
    }


def bench_e2e(quick=False):
    """Train-step throughput fed by the real input pipeline (EDLR file ->
    C++/Python reader -> Dataset shim -> host batches -> device)."""
    import tempfile

    import jax

    from elasticdl_tpu.data.data_reader import RecordIODataReader
    from elasticdl_tpu.data.dataset import Dataset
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.task_dispatcher import Task
    from elasticdl_tpu.common.constants import Mode, TaskType
    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    batch = 16 if quick else 64
    image = 64 if quick else 224
    records = batch * (4 if quick else 12)

    rng = np.random.default_rng(0)
    tmp = tempfile.mkdtemp(prefix="edl_bench_")
    path = os.path.join(tmp, "bench.edlr")
    with RecordIOWriter(path) as w:
        for _ in range(records):
            w.write(
                encode_example(
                    {
                        "image": rng.integers(
                            255, size=(image, image, 3), dtype=np.int64
                        ).astype(np.uint8),
                        "label": np.array(
                            [rng.integers(1, 1001)], dtype=np.int64
                        ),
                    }
                )
            )

    reader = RecordIODataReader(data_dir=tmp)

    def one_pass():
        task = Task(path, 0, records, TaskType.TRAINING)
        ds = Dataset.from_generator(
            lambda: iter(reader.read_records(task))
        )
        ds = zoo.dataset_fn(ds, Mode.TRAINING, None)
        # device_prefetch last: batches double-buffer onto the chip so
        # the h2d transfer overlaps the previous step's compute
        return ds.batch(batch).prefetch(2).device_prefetch()

    model = zoo.custom_model()
    first = next(iter(one_pass()))
    variables = init_variables(
        model,
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda x: np.asarray(x)[:1], first[0]),
    )
    params, state = split_variables(variables)
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)
    key = jax.random.PRNGKey(1)

    # warm both the compile cache and the reader page cache
    ts, loss = step_fn(ts, first[0], first[1], key)
    float(loss)

    t0 = time.perf_counter()
    n_examples = 0
    epochs = 1 if quick else 2
    for _ in range(epochs):
        for features, labels in one_pass():
            # shape check must not force a device->host fetch
            n = jax.tree_util.tree_leaves(labels)[0].shape[0]
            if n != batch:
                continue  # static-shape step; tail batch skipped
            ts, loss = step_fn(ts, features, labels, key)
            n_examples += n
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)
    return n_examples / dt


def bench_elastic_tax(quick=False):
    """Per-step tax of the elastic weighted-lockstep machinery on the
    visible chip: the SAME ResNet-50 config stepped through (a) the
    fused single-process step (training/step.py:make_train_step, donated
    args) and (b) the elastic step exactly as ElasticAllReduceWorker
    drives it — ``ElasticDPTrainer.train_step`` with deferred sync
    (sync_every=8, the worker's cadence), which adds weight scaling, the
    epoch-consensus pmax rider, per-step host batch placement, and
    no-donation double buffering (parallel/elastic.py:297-411).

    World formation is bypassed (1-device mesh built directly): the
    handshake is a reform-time cost, not a per-step one, and
    jax.distributed.initialize after the fused baseline has run would
    repin the backend.
    """
    import jax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.parallel import elastic as elastic_mod
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    batch = 32 if quick else 128
    image = 64 if quick else 224
    steps = 4 if quick else 24
    sync_every = 8

    model = zoo.custom_model()
    rng = np.random.default_rng(0)
    features = {
        "image": rng.random((batch, image, image, 3), dtype=np.float32)
    }
    labels = rng.integers(0, 1000, size=(batch, 1)).astype(np.int32)

    def measure_fused():
        variables = init_variables(
            model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
        )
        params, state = split_variables(variables)
        optimizer = zoo.optimizer()
        ts = TrainState.create(params, state, optimizer)
        step_fn = make_train_step(model, zoo.loss, optimizer)
        dev_features = jax.device_put(features)
        dev_labels = jax.device_put(labels)
        step_rng = jax.random.PRNGKey(1)
        for _ in range(2):
            ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
        float(loss)  # fetch-synchronized warmup (axon: see module doc)
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
        final = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final)
        return batch * steps / dt

    def build_trainer():
        from jax.sharding import Mesh

        from elasticdl_tpu.parallel.distributed import WorldSpec

        trainer = ElasticDPTrainer(model, zoo.loss, zoo.optimizer())
        trainer._spec = WorldSpec(
            coordinator="", num_processes=1, process_id=0, epoch=0
        )
        trainer._mesh = Mesh(
            np.asarray(jax.devices()[:1]), ("data",)
        )
        trainer._host_ts = trainer._host_init_ts((features, labels))
        trainer._ts = elastic_mod.broadcast_from_device0(
            trainer._mesh, trainer._host_ts
        )
        trainer._checked_ts = trainer._ts
        trainer._step_fn = elastic_mod.make_elastic_train_step(
            model, zoo.loss, trainer._optimizer, trainer._mesh
        )
        return trainer

    def measure_elastic_step(trainer):
        """The weighted-lockstep STEP FN alone (pre-placed inputs, same
        batch residency as the fused baseline): isolates the machinery
        tax — weight scaling, pmax rider, psum, no-donation double
        buffering — from input shipping."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = trainer._mesh
        put = lambda x: jax.device_put(  # noqa: E731
            x,
            NamedSharding(
                mesh, P(*(("data",) + (None,) * (np.asarray(x).ndim - 1)))
            ),
        )
        g_features = jax.tree_util.tree_map(put, features)
        g_labels = put(labels)
        g_w = jax.device_put(
            np.ones(1, np.float32), NamedSharding(mesh, P("data"))
        )
        g_ep = jax.device_put(
            np.zeros(1, np.int32), NamedSharding(mesh, P("data"))
        )
        key = jax.random.PRNGKey(1)
        ts = trainer._ts
        with mesh:
            for _ in range(2):
                ts, loss, n, _ = trainer._step_fn(
                    ts, g_features, g_labels, g_w, g_ep, key
                )
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                ts, loss, n, _ = trainer._step_fn(
                    ts, g_features, g_labels, g_w, g_ep, key
                )
            final = float(loss)
            dt = time.perf_counter() - t0
        assert np.isfinite(final)
        return batch * steps / dt

    def measure_elastic_worker_path(trainer):
        """The full ElasticAllReduceWorker driving shape: train_step with
        host batches (per-step placement) + deferred sync. Through the
        axon dev tunnel this is h2d-bound (~34 MB/s ships the 77 MB
        b128 batch), so it measures the tunnel, not the machinery —
        reported to stderr for the record, not as the metric."""

        def loop(n):
            for i in range(n):
                sync = (i + 1) % sync_every == 0 or i == n - 1
                loss, _, _ = trainer.train_step(
                    features, labels, batch, sync=sync
                )
            return loss

        loss = loop(2)
        assert np.isfinite(loss)
        n = max(4, steps // 4)  # tunnel-bound: keep the wait sane
        t0 = time.perf_counter()
        loss = loop(n)
        dt = time.perf_counter() - t0
        assert np.isfinite(loss)
        return batch * n / dt

    fused = measure_fused()
    trainer = build_trainer()
    elastic = measure_elastic_step(trainer)
    worker_path = measure_elastic_worker_path(trainer)
    overhead_pct = (fused - elastic) / fused * 100.0
    print(
        "elastic-tax: fused %.1f ex/s, elastic step fn %.1f ex/s, "
        "worker path (per-step host batch shipping; h2d-bound through "
        "the dev tunnel) %.1f ex/s" % (fused, elastic, worker_path),
        file=sys.stderr,
    )
    return overhead_pct, fused, elastic


def _force_cpu_mesh(n=8):
    """Pin this process to a CPU backend with ``n`` virtual devices.

    Must run before the FIRST jax backend initialization (XLA parses
    xla_force_host_platform_device_count at client creation); bench
    modes that need a multi-device mesh call it at the top of their
    main() branch, before any function imports jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % n
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    _force_cpu_backend()


def bench_compile(quick=False):
    """Compile-plane fast path A/B (docs/compile_plane.md), CPU mesh.

    Three resize arms drive the SAME elastic trainer journey — establish
    at 8 devices, train, shrink to 4, train, grow back to 8, train —
    and time each resize pause (host snapshot + mesh re-form + state
    re-broadcast + step acquisition + first step + fetch):

    - cold: executable cache disabled — every establish retraces and
      recompiles (the pre-compile-plane behavior);
    - cached: cache enabled — the return to 8 reuses the compiled
      executable (the >=3x acceptance arm); the first visit to 4 still
      pays a cold compile, which is that arm's WORST pause;
    - speculative: cache + background AOT compiles, hinted at the
      upcoming size during steady-state training — BOTH resizes find
      their executable ready, so the arm's worst pause undercuts the
      cached arm's.

    An equivalence pre-pass runs first: all three arms must finish the
    identical batch stream with BIT-IDENTICAL train state (a cached or
    speculatively-compiled executable that changed the math would be a
    correctness bug, not a speedup).

    A fourth measurement A/Bs the step-overlap machinery on the fixed
    8-device mesh: per-step blocking sync fetches vs deferred-sync
    dispatch with collect-later loss drains and feeder-thread H2D
    staging — both arms log EVERY step's loss, and the streams must be
    bitwise equal.
    """
    import jax
    from jax.sharding import Mesh

    from elasticdl_tpu.common.escapable import escapable_call
    from elasticdl_tpu.parallel import elastic as elastic_mod
    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    # one escapable device enumeration for every in-process resize
    all_devices = np.asarray(escapable_call(jax.devices, timeout=60.0))

    cfg = dict(
        vocab_size=256, num_layers=2, num_heads=4, head_dim=16,
        embed_dim=64, mlp_dim=128, use_flash=False,
    )
    batch, seq = 16, 32
    phase_steps = 4 if quick else 8
    model = zoo.custom_model(**cfg)

    rng = np.random.default_rng(0)

    def make_batches(n, seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            ids = r.integers(0, cfg["vocab_size"], size=(batch, seq))
            ids = ids.astype(np.int32)
            out.append(({"tokens": ids}, ids))
        return out

    phases = [  # (mesh size, batches) — identical stream in every arm
        (8, make_batches(phase_steps, 11)),
        (4, make_batches(phase_steps, 12)),
        (8, make_batches(phase_steps, 13)),
    ]

    def new_trainer(cache, speculative):
        import optax  # noqa: F401  (zoo.optimizer returns optax)

        t = ElasticDPTrainer(model, zoo.loss, zoo.optimizer())
        t.compile_cache_enabled = cache
        t.speculative_compile = speculative
        t.default_minibatch_size = batch
        t._spec = WorldSpec(
            coordinator="", num_processes=1, process_id=0, epoch=0
        )
        t._host_ts = t._host_init_ts(phases[0][1][0])
        return t

    def establish_at(t, k):
        """One in-process resize: re-form the mesh over the first k
        devices, re-broadcast state, acquire the step fn — the same
        phases ElasticPlane.establish times, minus the world RPC."""
        if t._ts is not None:
            t._host_ts = t.snapshot()
        t._mesh = Mesh(all_devices[:k], ("data",))
        t._ts = elastic_mod.broadcast_from_device0(t._mesh, t._host_ts)
        t._checked_ts = t._ts
        t._spec_example = phases[0][1][0]
        t._acquire_step_fn()

    def run_phase(t, batches):
        loss = None
        for features, labels in batches:
            loss, _, _ = t.train_step(features, labels, batch, sync=True)
        return loss

    def wait_speculation(t, deadline_s=300):
        sc = t._spec_compiler
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if sc is None or (sc.idle() and sc.pending_count() == 0):
                return
            time.sleep(0.05)

    def run_arm(cache, speculative):
        t = new_trainer(cache, speculative)
        pauses = {}
        final = None
        for i, (k, batches) in enumerate(phases):
            if speculative:
                # a hint from the previous steady-state phase must have
                # finished compiling before the resize pause is timed
                wait_speculation(t)
            t0 = time.perf_counter()
            establish_at(t, k)
            first = batches[0]
            t.train_step(first[0], first[1], batch, sync=True)
            pause = time.perf_counter() - t0
            if i > 0:  # the initial formation is not a resize
                pauses[(i, k)] = pause
            if speculative and i + 1 < len(phases):
                # steady-state hint for the NEXT size (the membership
                # service's role in a live job)
                if t._spec_compiler is None:
                    t._start_speculative_compiler()
                t.hint_world_sizes([phases[i + 1][0]])
            final = run_phase(t, batches[1:])
        assert np.isfinite(final)
        host = t.snapshot()
        stats = t.compile_stats.snapshot()
        t.close()
        return pauses, host, stats

    # equivalence pre-pass: bit-identical final state across arms
    cold_pauses, cold_state, _ = run_arm(cache=False, speculative=False)
    cached_pauses, cached_state, _ = run_arm(cache=True, speculative=False)
    spec_pauses, spec_state, spec_stats = run_arm(
        cache=True, speculative=True
    )
    ref = jax.tree_util.tree_leaves(cold_state.params)
    for name, state in (("cached", cached_state), ("speculative", spec_state)):
        got = jax.tree_util.tree_leaves(state.params)
        for a, b in zip(ref, got):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise RuntimeError(
                    "equivalence pre-pass failed: %s arm diverged from "
                    "the cold-compile arm" % name
                )

    revisit = (2, 8)  # the grow-back-to-8 resize (a previously-seen size)
    cold_revisit = cold_pauses[revisit]
    cached_revisit = cached_pauses[revisit]
    cached_worst = max(cached_pauses.values())
    spec_worst = max(spec_pauses.values())

    # step-overlap A/B on the fixed 8-device mesh; both arms record
    # EVERY step's loss (the sync arm by blocking each step, the
    # overlap arm by collect-later drains). Rep 0 runs from identical
    # fresh state in both arms and is the equivalence source; the
    # timing takes the best of the later reps (CPU scheduler noise on
    # a ~50ms step dwarfs the effect otherwise).
    overlap_batches = make_batches(24 if quick else 48, 21)

    def hot_loop_arm(overlap):
        t = new_trainer(cache=True, speculative=False)
        establish_at(t, 8)

        def one_rep():
            losses = []
            t0 = time.perf_counter()
            for i, (features, labels) in enumerate(overlap_batches):
                if overlap:
                    sync = (
                        (i + 1) % 8 == 0
                        or i == len(overlap_batches) - 1
                    )
                    if sync and i + 1 < len(overlap_batches):
                        # the worker's _peek_and_stage_next shape:
                        # batch N+1's H2D placement runs on the feeder
                        # thread while this sync step's fetch blocks
                        nf, nl = overlap_batches[i + 1]
                        t.stage_next(nf, nl, batch)
                    loss, _, _ = t.train_step(
                        features, labels, batch, sync=sync
                    )
                    if sync:
                        losses.extend(t.drain_metrics())
                        losses.append(loss)
                else:
                    loss, _, _ = t.train_step(
                        features, labels, batch, sync=True
                    )
                    losses.append(loss)
            wall = time.perf_counter() - t0
            return len(overlap_batches) * batch / wall, losses

        _, first_losses = one_rep()  # compile + equivalence stream
        eps = max(one_rep()[0] for _ in range(2 if quick else 3))
        t.close()
        return eps, first_losses

    sync_eps, sync_losses = hot_loop_arm(overlap=False)
    overlap_eps, overlap_losses = hot_loop_arm(overlap=True)
    if sync_losses != overlap_losses:
        raise RuntimeError(
            "step-overlap equivalence failed: deferred-collect loss "
            "stream differs from the per-step sync stream"
        )

    print(
        "compile-plane: cold revisit %.2fs, cached revisit %.2fs "
        "(%.1fx), worst pause cached %.2fs vs speculative %.2fs "
        "(%.1fx); hot loop sync %.0f ex/s vs overlap %.0f ex/s "
        "(%.2fx); spec stats %s"
        % (
            cold_revisit,
            cached_revisit,
            cold_revisit / max(cached_revisit, 1e-9),
            cached_worst,
            spec_worst,
            cached_worst / max(spec_worst, 1e-9),
            sync_eps,
            overlap_eps,
            overlap_eps / max(sync_eps, 1e-9),
            {
                k: v
                for k, v in spec_stats.items()
                if not k.endswith("_s")
            },
        ),
        file=sys.stderr,
    )
    return {
        "cold_revisit_s": cold_revisit,
        "cached_revisit_s": cached_revisit,
        "cached_worst_s": cached_worst,
        "spec_worst_s": spec_worst,
        "sync_eps": sync_eps,
        "overlap_eps": overlap_eps,
    }


def bench_resize(quick=False):
    """Elastic layout re-solve A/B (ISSUE 20; docs/distributed.md
    "Layout re-solve"), CPU mesh, single process, real ``establish()``.

    A transformer whose per-device memory budget rules out dp-only
    trains under a :class:`LayoutPlanner`. The journey: establish
    unbudgeted (the solver picks the dp-widest layout), train, then the
    budget lands (the over-budget moment) and the next establish
    re-solves to a tp>=2 layout, moving the state through the DIRECT
    relayout path. Two arms time that second establish + first step:

    - cold: executable cache disabled — the layout change pays a full
      re-trace/re-compile (the unplanned re-solve pause);
    - planned: cache + speculative AOT on — the planner's top-2 layout
      hints covered the post-budget winner during steady-state
      training, so the resize finds its executable pre-built.

    Gates (rc 1 on miss):
    - planned pause <= 0.5x the cold pause
      (resize_layout_speculative_pause_ratio);
    - the solver-chosen layout's measured examples/sec >= 1.0x naive
      dp-only at the micro-batch the budget admits dp-only
      (resize_solver_vs_naive_examples_ratio) — the budget here admits
      NO dp-only micro-batch, so naive runs charitably at the smallest
      table entry (a real dp-only job would simply OOM);
    - the relayout carries the train state BITWISE (params + optimizer
      slots), checked in the planned arm across the layout change.
    """
    import jax

    from elasticdl_tpu.parallel import distributed as dist_mod
    from elasticdl_tpu.parallel import layout_solver
    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
    from elasticdl_tpu.parallel.layout_solver import Layout, LayoutPlanner
    from model_zoo.transformer_lm import transformer_lm as zoo

    # single-process establish: the world RPC layer is not under test
    dist_mod.ensure_world = lambda spec, **kwargs: None

    cfg = dict(
        vocab_size=256, num_layers=2, num_heads=4, head_dim=16,
        embed_dim=64, mlp_dim=128, use_flash=False,
    )
    seq = 32
    steps = 6 if quick else 12
    model = zoo.custom_model(**cfg)

    def builder(mesh):
        # stable module identity: the speculative compile's cache key
        # includes id(module), so the builder must return THE model
        return model, zoo.param_shardings(mesh, tensor_parallel=2)

    def make_batches(n, rows, seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(n):
            ids = r.integers(0, cfg["vocab_size"], size=(rows, seq))
            ids = ids.astype(np.int32)
            out.append(({"tokens": ids}, ids))
        return out

    spec_of = lambda epoch: WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=epoch
    )

    def host_tree(ts):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), ts
        )

    def trees_equal(a, b):
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        return len(la) == len(lb) and all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(la, lb)
        )

    def budget_for(planner):
        """A per-device budget that rules dp-only OUT at every table
        micro-batch while admitting tp>=2 at the largest: the
        'over-budget transformer' of the acceptance gate, derived
        from the planner's own profile so it tracks the model."""
        prof = planner.profile
        return (
            prof.replicated_bytes
            + prof.tp_bytes / 2.0
            + prof.activation_bytes_per_row * max(planner.microbatches)
        )

    def wait_speculation(t, deadline_s=300):
        sc = t._spec_compiler
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if sc is None or (sc.idle() and sc.pending_count() == 0):
                return
            time.sleep(0.05)

    def measure_eps(t, batches, rows):
        t.train_step(batches[0][0], batches[0][1], rows, sync=True)
        t0 = time.perf_counter()
        for features, labels in batches[1:]:
            t.train_step(features, labels, rows, sync=True)
        wall = time.perf_counter() - t0
        return (len(batches) - 1) * rows / max(wall, 1e-9)

    # the job's GLOBAL batch is constant across the journey (elastic
    # resizes change the layout under the batch, not the batch): the
    # speculative AOT compiles against the last-trained batch shape,
    # so a shape change at the resize would defeat the pre-built
    # executable in both arms alike
    rows = 128

    def run_arm(cache, speculative):
        planner = LayoutPlanner(memory_budget=None)
        t = ElasticDPTrainer(
            model,
            zoo.loss,
            zoo.optimizer(),
            distributed_builder=builder,
            layout_planner=planner,
        )
        t.compile_cache_enabled = cache
        t.speculative_compile = speculative
        warm = make_batches(1, rows, 31)
        t.establish(spec_of(0), example_batch=warm[0])
        assert planner.profile is not None, "profile derivation failed"
        pre = planner.last_plan.layout
        # steady state on the unbudgeted layout (speculation, when on,
        # compiles the planner's top-2 hints for this size meanwhile)
        for features, labels in make_batches(3, rows, 32):
            t.train_step(features, labels, rows, sync=True)
        # the budget lands: next establish re-solves the layout
        planner.memory_budget = budget_for(planner)
        post = layout_solver.best(
            8, planner.profile, planner.memory_budget,
            planner.microbatches,
        ).layout
        if (post.dp, post.tp) == (pre.dp, pre.tp):
            raise RuntimeError(
                "budget did not force a layout change (%s -> %s)"
                % (pre, post)
            )
        if speculative:
            t.hint_world_sizes([8])
            wait_speculation(t)
        before = host_tree(t._ts)
        resize_batch = make_batches(1, rows, 33)[0]
        # pause = establish + first step; the bitwise relayout check
        # (a host pull) runs BETWEEN the two timed windows so it costs
        # neither, and before the step advances the state
        t0 = time.perf_counter()
        t.establish(spec_of(1), example_batch=resize_batch)
        establish_s = time.perf_counter() - t0
        preserved = trees_equal(before, host_tree(t._ts))
        t1 = time.perf_counter()
        t.train_step(resize_batch[0], resize_batch[1], rows, sync=True)
        pause = establish_s + (time.perf_counter() - t1)
        return t, planner, pre, post, pause, preserved

    # cold arm: the unplanned re-solve pause
    t_cold, _, _, _, cold_pause, _ = run_arm(
        cache=False, speculative=False
    )
    t_cold.close()
    # planned arm: layout-hinted speculation; also the bitwise gate
    # and the solver-arm throughput measurement
    t_plan, planner, pre, post, planned_pause, preserved = run_arm(
        cache=True, speculative=True
    )
    if not preserved:
        t_plan.close()
        raise RuntimeError(
            "direct relayout dropped state: train state differs "
            "across the %s -> %s layout change" % (pre, post)
        )
    solver_eps = measure_eps(
        t_plan, make_batches(steps + 1, rows, 41), rows
    )
    t_plan.close()

    # naive dp-only on the SAME over-budget model: the largest
    # micro-batch the budget admits for dp8 x tp1 (none here — run
    # charitably at the table's smallest)
    budget = planner.memory_budget
    naive_mb = None
    for mb in sorted(planner.microbatches, reverse=True):
        if layout_solver.device_bytes(
            Layout(8, 1, mb), planner.profile
        ) <= budget:
            naive_mb = mb
            break
    naive_mb = naive_mb or min(planner.microbatches)
    naive_rows = 8 * naive_mb
    t_naive = ElasticDPTrainer(
        model,
        zoo.loss,
        zoo.optimizer(),
        distributed_builder=builder,
        mesh_axes_fn=lambda n: {"data": 8, "model": 1},
    )
    t_naive.compile_cache_enabled = True
    warm = make_batches(1, naive_rows, 51)
    t_naive.establish(spec_of(0), example_batch=warm[0])
    naive_eps = measure_eps(
        t_naive, make_batches(steps + 1, naive_rows, 52), naive_rows
    )
    t_naive.close()

    print(
        "layout re-solve: %s -> %s; pause cold %.2fs vs planned %.2fs "
        "(ratio %.2f); solver %.0f ex/s (rows %d) vs naive dp-only "
        "%.0f ex/s (rows %d, ratio %.2f); state bitwise-preserved"
        % (
            (pre.dp, pre.tp, pre.microbatch),
            (post.dp, post.tp, post.microbatch),
            cold_pause,
            planned_pause,
            planned_pause / max(cold_pause, 1e-9),
            solver_eps,
            rows,
            naive_eps,
            naive_rows,
            solver_eps / max(naive_eps, 1e-9),
        ),
        file=sys.stderr,
    )
    return {
        "cold_pause_s": cold_pause,
        "planned_pause_s": planned_pause,
        "pause_ratio": planned_pause / max(cold_pause, 1e-9),
        "solver_eps": solver_eps,
        "naive_eps": naive_eps,
        "examples_ratio": solver_eps / max(naive_eps, 1e-9),
        "pre_layout": (pre.dp, pre.tp, pre.microbatch),
        "post_layout": (post.dp, post.tp, post.microbatch),
    }


def bench_preemption():
    """Wall-clock of the 3-process elastic allreduce job with one worker
    SIGKILLed mid-run, relative to the undisturbed run (CPU/gloo)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r); sys.path.insert(0, %r)\n"
        "from tests.test_elastic_allreduce import run_three_worker_job\n"
        "import tempfile, time, pathlib\n"
        # SAME config with and without the kill, so the difference is
        # the kill's cost alone (startup, formation, and the job's own
        # work cancel out)
        "t0 = time.time()\n"
        "run_three_worker_job(pathlib.Path(tempfile.mkdtemp()), kill=False)\n"
        "clean = time.time() - t0\n"
        "t0 = time.time()\n"
        "run_three_worker_job(pathlib.Path(tempfile.mkdtemp()), kill=True)\n"
        "killed = time.time() - t0\n"
        "import json\n"
        "print('PREEMPTION ' + json.dumps({'clean_s': round(clean, 1),"
        " 'killed_s': round(killed, 1)}))\n"
    ) % (here, here)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=here,
    )
    for line in proc.stdout.splitlines():
        if line.startswith("PREEMPTION "):
            return json.loads(line[len("PREEMPTION "):])
    raise RuntimeError(
        "preemption bench failed:\n" + proc.stdout[-2000:] + proc.stderr[-2000:]
    )


def bench_ps(quick=False):
    """Host-PS plane throughput (the reference's deployment shape):
    deepfm trained against 2 OS-process parameter servers over real
    loopback gRPC — async per-step push_gradient/pull round trips
    (reference ps/servicer.py:90-150) — with the bf16 wire compression
    off and on. Tells users when to pick the host-PS plane over the
    in-mesh HBM plane (BASELINE.md r5 row). The whole measurement runs
    in a CPU-forced subprocess: the host-PS plane is host-side by
    design, and the parent may hold (or be unable to reach) the
    accelerator. Returns {"examples_per_sec": X,
    "examples_per_sec_bf16": Y}."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, json\n"
        "print('PSBENCH ' + json.dumps(bench._bench_ps_impl(%r)))\n"
    ) % (here, quick)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=1800,
            cwd=here,
        )
    except subprocess.TimeoutExpired as e:
        # the PS grandchildren watch their parent's pid and exit with it
        raise RuntimeError(
            "ps bench timed out:\n%s" % str(e.stdout or "")[-2000:]
        ) from e
    for line in proc.stdout.splitlines():
        if line.startswith("PSBENCH "):
            return json.loads(line[len("PSBENCH "):])
    raise RuntimeError(
        "ps bench failed:\n" + proc.stdout[-2000:] + proc.stderr[-2000:]
    )


def bench_ps_device(quick=False):
    """Host-apply vs device-apply PS shard (docs/ps_device.md) at
    production payload sizes, in a CPU-forced subprocess (same
    containment as --ps). Returns the _bench_ps_device_impl dict:
    equivalence pre-pass verdicts + dense/sparse apply speedups."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, json\n"
        "print('PSBENCH ' + json.dumps(bench._bench_ps_device_impl(%r)))\n"
    ) % (here, quick)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=1800,
            cwd=here,
        )
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            "ps device bench timed out:\n%s" % str(e.stdout or "")[-2000:]
        ) from e
    for line in proc.stdout.splitlines():
        if line.startswith("PSBENCH "):
            return json.loads(line[len("PSBENCH "):])
    raise RuntimeError(
        "ps device bench failed:\n"
        + proc.stdout[-2000:]
        + proc.stderr[-2000:]
    )


def bench_tiered(quick=False):
    """Tiered embedding store (docs/tiered_store.md): a bitwise
    equivalence pre-pass (all-in-memory vs tiered PS shard from one
    common init), then the deepfm fleet job on a power-law id stream
    whose resident feature rows exceed the warm-tier budget 4x — the
    tiered arm must hold EDL_BENCH_TIERED_FLOOR (default 0.5x) of the
    all-in-memory arm's throughput while the ps_status counters prove
    the disk tier was actually exercised. CPU-forced subprocess (same
    containment as --ps). Returns the _bench_tiered_impl dict."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, json\n"
        "print('PSBENCH ' + json.dumps(bench._bench_tiered_impl(%r)))\n"
    ) % (here, quick)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=1800,
            cwd=here,
        )
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            "tiered bench timed out:\n%s" % str(e.stdout or "")[-2000:]
        ) from e
    for line in proc.stdout.splitlines():
        if line.startswith("PSBENCH "):
            return json.loads(line[len("PSBENCH "):])
    raise RuntimeError(
        "tiered bench failed:\n"
        + proc.stdout[-2000:]
        + proc.stderr[-2000:]
    )


def _bench_ps_device_impl(quick=False):
    """Measure the device-resident shard against the host shard on the
    two apply shapes that dominate a PS deployment (docs/ps_device.md):

    - **dense**: ~8 MiB full-model sgd push + pull_variable round.
      SGD on purpose: both planes run the SAME jitted step, so what
      separates them is the storage boundary this subsystem moved —
      the host arm's D2H writeback copy and pull-side staging — not
      optimizer flops. (adam's 7 compute passes would bury the
      boundary under math that is byte-identical work on both arms.)
    - **sparse**: a power-law (zipf) embedding id stream — duplicate
      ids, lazy init, adam slot tables (dim-64 rows, 2048-id pushes,
      50k vocab) — where the host arm walks the dict-of-rows store
      per row per table and the device arm runs one compiled
      gather/scatter per table over the arena.

    Both modes run at PRODUCTION payload sizes always; ``quick`` only
    trims rounds and steps, never shapes — the gate is defined at
    these shapes. Both servicer pairs run IN-PROCESS: this isolates
    the apply path — the wire cost is identical in both modes and
    already priced by the --ps fleet metrics.

    Protocol: a warmup pass drives the EXACT op/shape mix the timed
    pass uses (so every jit compile and lazy-init materialization —
    including the pull-shape gathers — lands outside the window; a
    production shard is measured at steady state, not during its
    first epoch), then host/device rounds alternate and each arm
    keeps its min-of-rounds per-step time (scheduler noise rejection).

    An equivalence pre-pass drives both modes through one identical
    stream per arm first and demands BITWISE-equal pulled params,
    embedding rows, and slot tables (the
    tests/test_ps_device_parity.py contract re-checked at bench
    shapes); the caller withholds the speedups unless it passes."""
    _force_cpu_backend()
    import numpy as np
    import optax

    from elasticdl_tpu.common.tensor import Tensor
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer

    # production payload sizes in BOTH modes (quick trims effort only);
    # the 32 MiB dense model deliberately exceeds L3 — at cache-resident
    # sizes the measurement is thread-pool noise, at DRAM sizes the host
    # arm's single-threaded staging copies are a structural cost
    dense_shape = (2048, 4096)
    dim, batch_ids, vocab = 64, 2048, 50_000
    rounds = 3 if quick else 5
    dense_steps = 4 if quick else 8
    sparse_steps = 6 if quick else 10
    warmup = 3

    rng = np.random.default_rng(0)
    w0 = rng.standard_normal(dense_shape).astype(np.float32)
    b0 = rng.standard_normal((dense_shape[1],)).astype(np.float32)
    dense_grads = [
        {
            "w": rng.standard_normal(dense_shape).astype(np.float32),
            "b": rng.standard_normal((dense_shape[1],)).astype(np.float32),
        }
        for _ in range(4)
    ]
    # power-law ids: head-heavy duplicates (the segment-sum combine
    # branch) with a long lazy-init tail
    sparse_stream = []
    for _ in range(sparse_steps):
        ids = ((rng.zipf(1.3, size=batch_ids) - 1) % vocab).astype(np.int64)
        sparse_stream.append(
            (ids, rng.standard_normal((batch_ids, dim)).astype(np.float32))
        )
    sparse_pull_ids = sparse_stream[0][0][:256]

    def mk_dense(device):
        s = PserverServicer(
            Parameters(device=device), 1, optax.sgd(0.05), use_async=True
        )
        s.push_model(
            {
                "version": 0,
                "params": [Tensor("w", w0.copy()), Tensor("b", b0.copy())],
                "embedding_infos": [],
            }
        )
        return s

    def mk_sparse(device):
        s = PserverServicer(
            Parameters(device=device), 1, optax.adam(1e-3), use_async=True
        )
        s.push_model(
            {
                "version": 0,
                "params": [],
                "embedding_infos": [{"name": "emb", "dim": dim}],
            }
        )
        return s

    def push_dense(servicer, step):
        g = dense_grads[step % len(dense_grads)]
        servicer.push_gradient(
            {
                "model_version": step,
                "gradients": [
                    Tensor("w", g["w"].copy()),
                    Tensor("b", g["b"].copy()),
                ],
            }
        )

    def push_sparse(servicer, step):
        ids, rows = sparse_stream[step % len(sparse_stream)]
        servicer.push_gradient(
            {
                "model_version": step,
                "gradients": [
                    Tensor("emb", rows.copy(), indices=ids.copy())
                ],
            }
        )

    # -- equivalence pre-pass: bitwise host == device per arm ----------
    pre_steps = 4
    probe_ids = np.arange(0, vocab, max(1, vocab // 512), dtype=np.int64)
    pulled = []
    for device in (False, True):
        s = mk_dense(device)
        for step in range(pre_steps):
            push_dense(s, step)
        dense = {
            t.name: np.asarray(t.values)
            for t in s.pull_variable({})["params"]
        }
        s = mk_sparse(device)
        for step in range(pre_steps):
            push_sparse(s, step)
        rows = np.asarray(
            s.pull_embedding_vector({"name": "emb", "ids": probe_ids})[
                "rows"
            ]
        )
        tables = {
            name: table.snapshot()
            for name, table in s._parameters.embedding_params.items()
        }
        pulled.append((dense, rows, tables))
    (hd, hr, ht), (dd, dr, dt) = pulled
    eq = {
        "dense_bitwise": all(
            np.array_equal(hd[k], dd[k]) for k in hd
        )
        and hd.keys() == dd.keys(),
        "rows_bitwise": np.array_equal(hr, dr),
        "slot_tables_bitwise": ht.keys() == dt.keys()
        and all(
            np.array_equal(ht[n][0], dt[n][0])
            and np.array_equal(ht[n][1], dt[n][1])
            for n in ht
        ),
    }
    eq["ok"] = all(eq.values())
    if not eq["ok"]:
        return {"equivalence": eq}

    # -- timed arms: steady-state warmup, alternating min-of-rounds ----
    def measure(mk, push, pull, steps, warm_steps):
        pair = {device: mk(device) for device in (False, True)}
        for device, s in pair.items():
            for step in range(warm_steps):
                push(s, step)
                pull(s)
        best = {False: float("inf"), True: float("inf")}
        for _ in range(rounds):
            for device, s in pair.items():
                t0 = time.perf_counter()
                for step in range(steps):
                    push(s, step)
                    pull(s)
                best[device] = min(
                    best[device], (time.perf_counter() - t0) / steps
                )
        return best[False], best[True]

    def pull_dense(s):
        s.pull_variable({})

    def pull_rows(s):
        s.pull_embedding_vector({"name": "emb", "ids": sparse_pull_ids})

    out = {"equivalence": eq}
    out["dense_host_s"], out["dense_device_s"] = measure(
        mk_dense, push_dense, pull_dense, dense_steps, warmup
    )
    # sparse warmup covers the WHOLE stream once: every id
    # materializes and every k_pad/capacity combo compiles before the
    # window opens (an arena growth mid-round is a recompile, and a
    # production shard past its first epoch doesn't pay those)
    out["sparse_host_s"], out["sparse_device_s"] = measure(
        mk_sparse, push_sparse, pull_rows, sparse_steps, len(sparse_stream)
    )
    out["dense_speedup"] = out["dense_host_s"] / max(
        out["dense_device_s"], 1e-9
    )
    out["sparse_speedup"] = out["sparse_host_s"] / max(
        out["sparse_device_s"], 1e-9
    )
    out["dense_mib"] = round(
        (w0.nbytes + b0.nbytes) / (1024.0 * 1024.0), 2
    )
    out["sparse_batch_ids"] = batch_ids
    out["rounds"] = rounds
    return out


def _on_cpu():
    """True when the measured backend is plain CPU: device sections
    shrink their workloads (a production-sized ResNet-50 step on CPU
    eats the whole suite budget — the BENCH_r05 wedge) and publish
    under a ``_cpu`` metric suffix so accelerator ratchets stay
    unpoisoned."""
    import jax

    return jax.default_backend() == "cpu"


def _run_section_cmd(cmd, timeout):
    """Run one suite section with a HARD timeout.

    ``subprocess.run(timeout=...)`` kills only the direct child, then
    blocks draining its pipes — which stay open as long as any
    grandchild (PS fleets, elastic worker processes) inherited them, so
    a wedged section could outlive its "hard" timeout indefinitely
    (half of the BENCH_r05 rc=124). The section therefore runs in its
    own process GROUP and the whole group is SIGKILLed on expiry, with
    a bounded second drain. Returns (rc, stdout, stderr, timed_out)."""
    import signal
    import subprocess

    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return proc.returncode, stdout, stderr, False
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=10)
        except (subprocess.TimeoutExpired, ValueError, OSError):
            stdout, stderr = "", ""
        return -9, stdout or "", stderr or "", True


def _force_cpu_backend():
    """Pin jax to CPU in THIS process (a sitecustomize may have pinned
    an accelerator platform via jax.config, so env vars alone do not
    stick — same recipe as tests/conftest.py)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax.extend.backend import clear_backends
    except ImportError:
        clear_backends = getattr(jax, "clear_backends", None)
    if clear_backends is not None:
        clear_backends()


# PS bootstrap: CPU-forced, and a parent-death watchdog so a killed
# bench driver (subprocess timeout) cannot leak PS grandchildren.
# Shared by every fleet-driving arm (--ps, --hybrid).
def _ps_fleet_boot_code():
    here = os.path.dirname(os.path.abspath(__file__))
    return (
        "import os, sys, threading, time\n"
        "sys.path.insert(0, %r)\n"
        "import bench\n"
        "bench._force_cpu_backend()\n"
        "_parent = os.getppid()\n"
        "def _watch():\n"
        "    while os.getppid() == _parent:\n"
        "        time.sleep(1.0)\n"
        "    os._exit(0)\n"
        "threading.Thread(target=_watch, daemon=True).start()\n"
        "from elasticdl_tpu.ps.parameter_server import ParameterServer\n"
        "from elasticdl_tpu.common.args import parse_ps_args\n"
        "server = ParameterServer(parse_ps_args(sys.argv[1:]))\n"
        "server.prepare()\n"
        "server.run()\n"
    ) % here


def _wait_ps_port(proc, err, port, deadline):
    import socket

    while True:
        if proc.poll() is not None:
            err.flush()
            raise RuntimeError(
                "PS exited rc=%d at boot: %s"
                % (
                    proc.returncode,
                    open(err.name, "rb").read()[-2000:],
                )
            )
        try:
            with socket.create_connection(("localhost", port), 1.0):
                return
        except OSError:
            if time.time() > deadline:
                raise RuntimeError(
                    "PS did not come up: %s"
                    % open(err.name, "rb").read()[-2000:]
                )
            time.sleep(0.2)


def _launch_ps_fleet_ex(
    err_dir, model_zoo, model_def, tag, extra_args=(), n=2
):
    """Launch ``n`` real async PS OS processes and wait for their ports.

    Returns (procs, addrs, cmds, env) — ``cmds[i]`` is shard i's full
    argv, so a chaos driver can relaunch a killed shard with the SAME
    id/port (the instance-manager contract). Stop with
    :func:`_stop_ps_fleet`. The bind-then-close port picking has a
    TOCTOU window; a lost race surfaces through the per-process stderr
    files in ``err_dir`` instead of silently."""
    import socket
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    ps_boot = _ps_fleet_boot_code()
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        ports.append(s.getsockname()[1])
        s.close()
    procs, cmds = [], []
    for i, port in enumerate(ports):
        err = open(
            os.path.join(err_dir, "ps-%s-%d.err" % (tag, i)), "ab"
        )
        cmd = [
            sys.executable, "-c", ps_boot,
            "--ps_id", str(i),
            "--port", str(port),
            "--model_zoo", model_zoo,
            "--model_def", model_def,
            "--use_async", "true",
            "--grads_to_wait", "1",
        ] + list(extra_args)
        cmds.append(cmd)
        procs.append(
            (
                subprocess.Popen(
                    cmd,
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=err,
                ),
                err,
            )
        )
    deadline = time.time() + 60
    for (proc, err), port in zip(procs, ports):
        _wait_ps_port(proc, err, port, deadline)
    return procs, ["localhost:%d" % p for p in ports], cmds, env


def _launch_ps_fleet(err_dir, model_zoo, model_def, tag, extra_args=(), n=2):
    """Historical (procs, addrs) form of :func:`_launch_ps_fleet_ex`."""
    procs, addrs, _, _ = _launch_ps_fleet_ex(
        err_dir, model_zoo, model_def, tag, extra_args=extra_args, n=n
    )
    return procs, addrs


def _stop_ps_fleet(procs):
    for proc, _ in procs:
        proc.terminate()
    for proc, err in procs:
        try:
            proc.wait(timeout=10)
        except Exception:
            proc.kill()
        err.close()


# Every bench-launched fleet process (PS shards, scorers) boots through
# a `python -c` snippet containing this exact line — the marker the
# stale-process reaper keys on.
_FLEET_BOOT_MARKER = "bench._force_cpu_backend()"


def _reap_stale_fleet():
    """SIGKILL leaked fleet processes from aborted earlier drives.

    The PR-9 caution, made automatic: a PS (or scorer) process orphaned
    by an aborted manual drive keeps its port and its CPU share and
    silently poisons later bench arms' measurements. Every
    bench-launched fleet child carries the boot-code marker in its -c
    argv and a parent-death watchdog; this pre-run guard catches the
    cases the watchdog cannot (a re-parented child whose new ancestor
    lives on). Matching is strictly on the marker — test-launched
    ``ps.main`` processes and anything else are never touched. Shared
    by every fleet-driving arm (--ps, --hybrid, --chaos, --serve)."""
    import signal

    me = os.getpid()
    reaped = []
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return reaped  # no /proc (non-linux): nothing to do
    for pid_s in pids:
        pid = int(pid_s)
        if pid == me:
            continue
        try:
            with open("/proc/%d/cmdline" % pid, "rb") as f:
                cmdline = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if _FLEET_BOOT_MARKER not in cmdline:
            continue
        try:
            os.kill(pid, signal.SIGKILL)
            reaped.append(pid)
        except (ProcessLookupError, PermissionError):
            continue
    if reaped:
        print(
            "reaped %d stale fleet process(es) from an earlier "
            "aborted drive: %s" % (len(reaped), reaped),
            file=sys.stderr,
        )
    return reaped


def _bench_ps_impl(quick=False):
    import tempfile

    _force_cpu_backend()
    _reap_stale_fleet()

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    records = 512 if quick else 4096
    batch = 32
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"

    def launch_fleet(wire, err_dir, tag=None, extra_args=()):
        return _launch_ps_fleet(
            err_dir,
            MODEL_ZOO_PATH,
            model_def,
            tag or wire or "f32",
            extra_args=["--wire_dtype", wire] + list(extra_args),
        )

    stop_fleet = _stop_ps_fleet

    def run_job(
        addrs,
        wire,
        data,
        n,
        sparse_dedup=True,
        ps_kwargs=None,
        batch_size=None,
        params=None,
        get_model_steps=1,
    ):
        batch_size = batch_size or batch
        shards = {data: (0, n)}
        task_d = TaskDispatcher(shards, {}, {}, batch_size * 4, 1)
        master = MasterServicer(
            1,
            batch_size,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        ps_client = PSClient(
            [BoundPS(a) for a in addrs],
            wire_dtype=wire,
            **(ps_kwargs or {}),
        )
        worker = Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=batch_size,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
            model_params=params or model_params,
            ps_client=ps_client,
            sparse_dedup=sparse_dedup,
            get_model_steps=get_model_steps,
        )
        worker._stub = InProcessMaster(master)
        t0 = time.perf_counter()
        try:
            worker.run()
        finally:
            # a failed arm must not leak fan-out/push threads and
            # channels into the rest of the suite
            ps_client.close()
        dt = time.perf_counter() - t0
        if not task_d.finished():
            raise RuntimeError("PS bench job did not finish")
        return n / dt

    def powerlaw_frappe_file(n, tmp):
        """FRAPPE-schema file whose ids are zipf-drawn from a 64-id
        pool: each 32-example batch carries 320 ids but <= 64 distinct
        (>= 5x average duplication) — the recommendation-workload shape
        the uniform-random create_recordio_file never produces."""
        from elasticdl_tpu.data.example import encode_example
        from elasticdl_tpu.data.recordio import RecordIOWriter

        rng = np.random.default_rng(7)
        pool = rng.permutation(5383)[:64]
        weights = 1.0 / np.arange(1, 65) ** 1.1
        weights /= weights.sum()
        path = os.path.join(tmp, "frappe_powerlaw_%d.edlr" % n)
        with RecordIOWriter(path) as f:
            for _ in range(n):
                f.write(
                    encode_example(
                        {
                            "feature": rng.choice(
                                pool, size=(10,), p=weights
                            ).astype(np.int64),
                            "label": np.array(
                                [rng.integers(2)], dtype=np.int64
                            ),
                        }
                    )
                )
        return path

    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        f = create_recordio_file(
            records, DatasetName.FRAPPE, 10, temp_dir=tmp
        )
        warm = create_recordio_file(
            batch * 4, DatasetName.FRAPPE, 10, temp_dir=tmp
        )
        # a FRESH fleet per arm so BOTH directions carry the arm's wire
        # dtype (the PS compresses pulls per ITS flag — a shared fleet
        # would leave the pull direction f32 in the bf16 arm); the
        # warmup job per arm pays the worker jit compiles (first arm
        # only — the process-level cache persists) and the fleet's
        # lazy init (every arm), keeping the A/B symmetric
        for wire in ("", "bfloat16"):
            procs, addrs = launch_fleet(wire, tmp)
            try:
                run_job(addrs, wire, warm, batch * 4)
                eps = run_job(addrs, wire, f, records)
            finally:
                stop_fleet(procs)
            key = (
                "examples_per_sec_bf16" if wire else "examples_per_sec"
            )
            results[key] = eps

        # duplicated-ID arms: the sparse-comms fast path (batch dedup +
        # row-combined push + hot-row cache, docs/sparse_fast_path.md)
        # vs the naive per-occurrence plane, both on the SAME power-law
        # file and the SAME recommendation-shaped config — batch 512
        # and 256-dim rows, where the sparse plane is the bottleneck
        # (5120 ids/batch, <= 64 distinct: the naive plane ships
        # ~5.2 MB of duplicate rows each way per step and pads its
        # jitted gather to the next pow2 bucket, 8192 rows). Fresh
        # fleet per arm: each must pay its own lazy table init and see
        # untouched versions.
        dup_batch = 64 if quick else 512
        dup_params = "embedding_dim=256,fc_unit=16,vocab_size=5383"
        dup_records = dup_batch * (4 if quick else 24)
        dup_f = powerlaw_frappe_file(dup_records, tmp)
        dup_warm = powerlaw_frappe_file(dup_batch * 2, tmp)
        arms = {
            "examples_per_sec_dup_naive": dict(
                sparse_dedup=False,
                ps_kwargs=dict(combine_push=False),
            ),
            "examples_per_sec_fastpath": dict(
                sparse_dedup=True,
                ps_kwargs=dict(
                    combine_push=True,
                    hot_row_cache_rows=4096,
                    staleness_window=4,
                ),
            ),
        }
        for key, arm in arms.items():
            procs, addrs = launch_fleet("", tmp, tag="dup-" + key[-8:])
            try:
                run_job(
                    addrs,
                    "",
                    dup_warm,
                    dup_batch * 2,
                    batch_size=dup_batch,
                    params=dup_params,
                    **arm,
                )
                results[key] = run_job(
                    addrs,
                    "",
                    dup_f,
                    dup_records,
                    batch_size=dup_batch,
                    params=dup_params,
                    **arm,
                )
            finally:
                stop_fleet(procs)

        # overlapped-data-plane arms (docs/dense_overlap.md): the SAME
        # deepfm workload against the SAME fleet, driven through (a)
        # the strictly serial per-shard loop with synchronous pushes —
        # the pre-overlap client — and (b) concurrent shard fan-out
        # plus the double-buffered async push window. Both fleets get
        # --rpc_inject_delay_ms: on a loopback bench every RPC leg is
        # CPU work on the same cores, so serial-vs-overlap would only
        # measure scheduler thrash; a real PS fleet lives across pods
        # where each leg carries genuine network latency — the exact
        # idle time the serial loop multiplies by shard count and the
        # overlap reclaims. get_model_steps=4 gives the async window
        # real compute to hide behind between pulls (pulls drain the
        # window, so staleness never leaves the SSP bound the LR
        # modulation already prices in).
        overlap_rtt_ms = 30.0
        overlap_arms = {
            "examples_per_sec_serial": dict(
                ps_kwargs=dict(fanout=False, push_inflight=0)
            ),
            "examples_per_sec_overlap": dict(
                ps_kwargs=dict(fanout=True, push_inflight=1)
            ),
        }
        results["overlap_rtt_ms"] = overlap_rtt_ms
        for key, arm in overlap_arms.items():
            procs, addrs = launch_fleet(
                "",
                tmp,
                tag="ov-" + key[-7:],
                extra_args=[
                    "--rpc_inject_delay_ms", str(overlap_rtt_ms)
                ],
            )
            try:
                run_job(
                    addrs,
                    "",
                    warm,
                    batch * 4,
                    get_model_steps=4,
                    **arm,
                )
                results[key] = run_job(
                    addrs,
                    "",
                    f,
                    records,
                    get_model_steps=4,
                    **arm,
                )
            finally:
                stop_fleet(procs)
    results.update(_bench_ps_fanout_microbench(quick))
    return results


def _bench_ps_fanout_microbench(quick=False):
    """Slow-shard fan-out microbench: 4 in-process PS stubs, one 4x
    slower than the rest (tests/fake_ps fault injection). The serial
    loop pays the SUM of shard latencies per logical call; the fan-out
    pays only the slowest shard. Returns per-call walls plus the
    analytic sum/max so the suite line can show which one the measured
    wall tracks."""
    from elasticdl_tpu.worker.ps_client import PSClient
    from tests.fake_ps import FaultyPS, TablePS

    shards, fast_s, slow_s = 4, 0.02, 0.08
    reps = 3 if quick else 10
    ids = np.arange(64, dtype=np.int64)

    def fleet():
        return [
            FaultyPS(
                TablePS(dim=8),
                delay_s=(slow_s if i == shards - 1 else fast_s),
            )
            for i in range(shards)
        ]

    walls = {}
    for key, fanout in (("serial", False), ("fanout", True)):
        client = PSClient(fleet(), fanout=fanout)
        client.pull_embedding_vectors("emb", ids)  # pool/JIT warmup
        t0 = time.perf_counter()
        for _ in range(reps):
            client.pull_embedding_vectors("emb", ids)
        walls[key] = (time.perf_counter() - t0) / reps
        client.close()
    return {
        "fanout_serial_call_s": walls["serial"],
        "fanout_overlap_call_s": walls["fanout"],
        "fanout_slowest_shard_s": slow_s,
        "fanout_shard_sum_s": fast_s * (shards - 1) + slow_s,
    }


def _bench_tiered_equivalence(quick, tmp):
    """Bitwise equivalence pre-pass: one all-in-memory and one tiered
    PS shard, in-process, driven from ONE common init (the splitmix64
    id-keyed lazy init makes both arms mint identical rows) through an
    identical power-law lookup/push stream. The tiered arm runs a tiny
    warm budget so promotion/demotion churns on every step; lookups,
    applied rows, and the final full-table read must all match bitwise
    — a tier move that drops, duplicates or stales a single row fails
    here before any throughput is measured."""
    import optax

    from elasticdl_tpu.common.tensor import Tensor
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer

    dim, warm_rows, pool_n = 16, 64, 512
    steps = 8 if quick else 24
    rng = np.random.default_rng(11)
    pool = rng.permutation(5383)[:pool_n]
    w = 1.0 / np.arange(1, pool_n + 1) ** 1.2
    w /= w.sum()
    stream = [
        np.unique(rng.choice(pool, size=96, p=w)).astype(np.int64)
        for _ in range(steps)
    ]
    grads = [
        rng.standard_normal((len(ids), dim)).astype(np.float32)
        for ids in stream
    ]

    def mk(tier):
        p = Parameters(tier_config=tier)
        s = PserverServicer(p, 1, optax.adam(0.05), use_async=True)
        s.push_model(
            {
                "version": 0,
                "params": [Tensor("w", np.ones((4, 4), np.float32))],
                "embedding_infos": [{"name": "emb", "dim": dim}],
            }
        )
        return p, s

    def rows_of(s, ids):
        return np.asarray(
            s.pull_embedding_vector({"name": "emb", "ids": ids})["rows"]
        )

    p_mem, s_mem = mk(None)
    p_tier, s_tier = mk(
        {
            "warm_rows": warm_rows,
            "spill_dir": os.path.join(tmp, "eq-spill"),
        }
    )
    verdict = {"lookups": True, "applied_rows": True, "full_table": True}
    try:
        for step, (ids, g) in enumerate(zip(stream, grads)):
            if not np.array_equal(rows_of(s_mem, ids), rows_of(s_tier, ids)):
                verdict["lookups"] = False
            req = {
                "model_version": step,
                "gradients": [Tensor("emb", g, indices=ids)],
            }
            s_mem.push_gradient(dict(req))
            s_tier.push_gradient(dict(req))
            if not np.array_equal(rows_of(s_mem, ids), rows_of(s_tier, ids)):
                verdict["applied_rows"] = False
        # force the disk tier into play before the full-table read: the
        # pre-pass must prove equivalence ACROSS a tier crossing, not
        # on a lucky all-warm run
        table = p_tier.embedding_params["emb"]
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if table.stats()["disk_rows"] > 0:
                break
            table.signal_pressure()
            time.sleep(0.02)
        every = np.sort(np.unique(np.concatenate(stream)))
        if not np.array_equal(rows_of(s_mem, every), rows_of(s_tier, every)):
            verdict["full_table"] = False
        st = table.stats()
        verdict["spilled"] = st["spilled_rows"] > 0
        verdict["cold_pulled"] = st["cold_pull_rows"] > 0
    finally:
        p_tier.close()
        p_mem.close()
    verdict["ok"] = all(verdict.values())
    return verdict


def _bench_tiered_impl(quick=False):
    """Equivalence pre-pass (in-process), then the A/B fleet drive:
    the SAME deepfm job on a zipf id stream against (a) an untiered
    2-process PS fleet and (b) the same fleet with --ps_warm_rows /
    --ps_spill_dir sized so the resident feature rows are >= 4x the
    warm budget. Returns throughputs plus the summed ps_status
    'tiered' counters of the tiered fleet — the caller gates on them
    (spilled_rows > 0, cold_pull_rows > 0) plus the per-shard
    distinct-id counts proving the table outgrows the warm budget."""
    import tempfile

    _force_cpu_backend()
    _reap_stale_fleet()

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import MODEL_ZOO_PATH

    batch = 32
    records = 256 if quick else 2048
    warm_rows = 64
    pool_n = 2048
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"

    def zipf_frappe_file(n, tmp, name):
        """FRAPPE-schema file, ids zipf-drawn from a pool far larger
        than the warm budget: the head stays warm, the long tail
        spills and recurs — the disk-tier workload shape. Returns
        (path, per-shard distinct-id counts) so the caller can PROVE
        the feature table outgrows the warm tier on every shard
        (PSClient routes id -> id %% num_ps)."""
        from elasticdl_tpu.data.example import encode_example
        from elasticdl_tpu.data.recordio import RecordIOWriter

        rng = np.random.default_rng(13)
        pool = rng.permutation(5383)[:pool_n]
        w = 1.0 / np.arange(1, pool_n + 1) ** 1.05
        w /= w.sum()
        path = os.path.join(tmp, "%s_%d.edlr" % (name, n))
        seen = set()
        with RecordIOWriter(path) as f:
            for _ in range(n):
                ids = rng.choice(pool, size=(10,), p=w).astype(np.int64)
                seen.update(int(i) for i in ids)
                f.write(
                    encode_example(
                        {
                            "feature": ids,
                            "label": np.array(
                                [rng.integers(2)], dtype=np.int64
                            ),
                        }
                    )
                )
        per_shard = [
            sum(1 for i in seen if i % 2 == s) for s in range(2)
        ]
        return path, per_shard

    def run_job(addrs, data, n):
        shards = {data: (0, n)}
        task_d = TaskDispatcher(shards, {}, {}, batch * 4, 1)
        master = MasterServicer(
            1,
            batch,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        ps_client = PSClient([BoundPS(a) for a in addrs])
        worker = Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=batch,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
            model_params=model_params,
            ps_client=ps_client,
            sparse_dedup=True,
        )
        worker._stub = InProcessMaster(master)
        t0 = time.perf_counter()
        try:
            worker.run()
        finally:
            ps_client.close()
        dt = time.perf_counter() - t0
        if not task_d.finished():
            raise RuntimeError("tiered bench job did not finish")
        return n / dt

    def probe_tiered(addrs):
        """Summed ps_status 'tiered' counters + the per-shard list."""
        shards = []
        for a in addrs:
            c = BoundPS(a, deadline_s=10.0)
            try:
                shards.append(dict(c.ps_status({}).get("tiered") or {}))
            finally:
                c.close()
        total = {}
        for st in shards:
            for k, v in st.items():
                if isinstance(v, (int, float)):
                    total[k] = total.get(k, 0) + v
        return total, shards

    results = {"warm_rows": warm_rows, "pool_ids": pool_n}
    with tempfile.TemporaryDirectory() as tmp:
        results["equivalence"] = _bench_tiered_equivalence(quick, tmp)
        if not results["equivalence"]["ok"]:
            return results  # no point timing a wrong store

        f, per_shard = zipf_frappe_file(records, tmp, "zipf")
        warm_f, _ = zipf_frappe_file(batch * 4, tmp, "zipf_warm")
        results["distinct_rows_per_shard"] = per_shard
        arms = {
            "examples_per_sec_memory": [],
            "examples_per_sec_tiered": [
                "--ps_warm_rows", str(warm_rows),
                "--ps_spill_dir", os.path.join(tmp, "spill"),
            ],
        }
        for key, extra in arms.items():
            procs, addrs = _launch_ps_fleet(
                tmp,
                MODEL_ZOO_PATH,
                model_def,
                "tier-" + key[-6:],
                extra_args=extra,
            )
            try:
                run_job(addrs, warm_f, batch * 4)
                results[key] = run_job(addrs, f, records)
                if extra:
                    total, shards = probe_tiered(addrs)
                    results["tiered_counters"] = total
                    results["tiered_counters_per_shard"] = shards
            finally:
                _stop_ps_fleet(procs)
    return results


def bench_chaos(quick=False):
    """Fleet chaos drive (docs/ps_recovery.md): the same deepfm job
    against a 2-OS-process PS fleet, once fault-free and once with a
    scripted SIGKILL of one shard mid-job under a versioned snapshot
    cadence. The killed shard is relaunched with the same id/port; the
    job must run to completion with the worker's reconnect protocol
    (cache invalidated, in-flight push window dropped — never resent —
    `ps_shard_failure`→`ps_shard_restore` telemetry emitted), and the
    final dense parameters must sit within the snapshot-staleness bound
    of the fault-free run — operationally gated as "far closer to the
    fault-free params than to near-init params" (the silent-reinit
    hazard this plane removes) plus a rollback depth <= the cadence.
    CPU-forced subprocess, same containment as --ps."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, json\n"
        "print('CHAOSBENCH ' + json.dumps(bench._bench_chaos_impl(%r)))\n"
    ) % (here, quick)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=1800,
            cwd=here,
        )
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            "chaos bench timed out:\n%s" % str(e.stdout or "")[-2000:]
        ) from e
    for line in proc.stdout.splitlines():
        if line.startswith("CHAOSBENCH "):
            return json.loads(line[len("CHAOSBENCH "):])
    raise RuntimeError(
        "chaos bench failed:\n"
        + proc.stdout[-2000:]
        + proc.stderr[-2000:]
    )


def _bench_chaos_impl(quick=False):
    """Three arms on identical data/seed: fault-free; SIGKILL-one-shard
    WITH the snapshot cadence (the recovery plane); SIGKILL-one-shard
    WITHOUT durability (today's silent-reinit hazard — the shard comes
    back empty and the worker's re-push restores only dense params and
    table metadata, so trained EMBEDDING rows reset to init). The gate
    compares each chaos arm's final state (dense params + every trained
    embedding row) against the fault-free run: the restored arm must
    land far closer than the reinit arm does."""
    import tempfile
    import threading

    _force_cpu_backend()
    _reap_stale_fleet()

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.tools.chaos import ChaosOp, FleetChaos
    from elasticdl_tpu.utils import profiling
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import MODEL_ZOO_PATH

    # Deterministic trajectory contract: the divergence gate compares
    # three runs, so everything except the injected fault must be
    # bit-reproducible. Two entropy sources are pinned here, in this
    # CPU-forced bench subprocess only: (1) the zoo dataset_fn's
    # unseeded shuffle becomes the identity (records train in file
    # order — the file is already drawn from seeded pools), and (2)
    # the worker runs the strictly-ordered client config
    # (push_inflight=0, no hot-row cache) because the overlapped
    # window/cache hit pattern is thread-timing-dependent and measured
    # fault-free run-to-run L2 noise from it (~1.4) exceeded the
    # restore-vs-reinit signal. The cache-invalidation and
    # window-abandonment halves of the reconnect protocol are pinned
    # by tests/test_chaos.py and tests/test_ps_fleet_recovery.py.
    from elasticdl_tpu.data import dataset as _dataset_mod

    _dataset_mod.Dataset.shuffle = (
        lambda self, buffer_size, seed=None,
        reshuffle_each_iteration=True: self
    )

    records = 512 if quick else 1536
    batch = 32
    cadence = 3 if quick else 4
    # kill mid-job: right around the early->late pool handover below,
    # so the early pool's rows see no organic retraining afterwards
    kill_at_version = (records // batch) // 2 + 2
    pool_size = 96
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"
    # the deepfm zoo's two PS tables; probed row-by-row for the gate
    tables = ("embedding", "id_bias")

    def pooled_frappe_file(n, tmp, name, pools):
        """FRAPPE-schema file drawing ids from ``pools`` — one pool per
        consecutive half of the records. The gate probes the EARLY
        pool: its rows train many times before the mid-job kill and
        (in the main file) never again after, so their final values
        discriminate a restored table (rows keep their trained values
        minus at most the cadence rollback) from a silently
        re-initialized one (rows reset to fresh init) without the
        wash-out of continued retraining."""
        from elasticdl_tpu.data.example import encode_example
        from elasticdl_tpu.data.recordio import RecordIOWriter

        rng = np.random.default_rng(13)
        path = os.path.join(tmp, "%s_%d.edlr" % (name, n))
        per_pool = (n + len(pools) - 1) // len(pools)
        with RecordIOWriter(path) as f:
            for i in range(n):
                pool = pools[min(i // per_pool, len(pools) - 1)]
                f.write(
                    encode_example(
                        {
                            "feature": rng.choice(
                                pool, size=(10,)
                            ).astype(np.int64),
                            "label": np.array(
                                [rng.integers(2)], dtype=np.int64
                            ),
                        }
                    )
                )
        return path

    def run_job(addrs, data, n):
        shards = {data: (0, n)}
        task_d = TaskDispatcher(shards, {}, {}, batch * 4, 1)
        master = MasterServicer(
            1,
            batch,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        ps_client = PSClient(
            [
                BoundPS(a, deadline_s=5.0, retries=2, backoff_s=0.2)
                for a in addrs
            ],
            # strictly-ordered config: see the determinism note above
            hot_row_cache_rows=0,
            push_inflight=0,
        )
        worker = Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=batch,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
            model_params=model_params,
            ps_client=ps_client,
            seed=7,
        )
        worker._stub = InProcessMaster(master)
        try:
            worker.run()
        finally:
            ps_client.close()
        if not task_d.finished():
            raise RuntimeError("chaos bench job did not finish")

    def fleet_state(addrs, probe_ids):
        """(version, flat float64 vector of dense params + every probe
        row of both tables) — the gate's comparison space."""
        client = PSClient([BoundPS(a, deadline_s=10.0) for a in addrs])
        try:
            ok, version, named = client.pull_dense()
            if not ok:
                raise RuntimeError(
                    "fleet reports uninitialized dense params"
                )
            rows = client.pull_embedding_vectors_multi(
                {name: probe_ids for name in tables}
            )
        finally:
            client.close()
        parts = [
            np.asarray(named[k], np.float64).ravel()
            for k in sorted(named)
        ]
        parts += [
            np.asarray(rows[name], np.float64).ravel() for name in tables
        ]
        return version, np.concatenate(parts)

    def run_chaos_arm(tag, extra_args, data, warm):
        """One kill-one-shard job; returns (results_dict, state)."""
        procs, addrs, cmds, env = _launch_ps_fleet_ex(
            tmp, MODEL_ZOO_PATH, model_def, tag, extra_args=extra_args
        )
        schedule = [ChaosOp("kill", 0, at_version=kill_at_version)]
        relaunched = threading.Event()

        class _Fleet:
            """kill_ps = SIGKILL + relaunch with the same argv/port —
            the LocalInstanceManager relaunch contract, driven by the
            bench's own process table."""

            def kill_ps(self, shard):
                import subprocess

                proc, err = procs[shard]
                proc.kill()
                proc.wait(timeout=10)
                procs[shard] = (
                    subprocess.Popen(
                        cmds[shard],
                        env=env,
                        stdout=subprocess.DEVNULL,
                        stderr=err,
                    ),
                    err,
                )
                relaunched.set()

            terminate_ps = kill_ps

        from elasticdl_tpu.rpc.core import Client

        status_clients = [Client(a, deadline_s=2.0) for a in addrs]

        def status_fn(shard):
            return status_clients[shard].call("ps_status")

        profiling.events.reset()
        chaos = FleetChaos(
            _Fleet(), status_fn, schedule, poll_s=0.2
        ).start()
        arm = {}
        try:
            run_job(addrs, warm, batch * 2)
            run_job(addrs, data, records)
            chaos.stop()
            if not chaos.done():
                raise RuntimeError(
                    "chaos schedule did not execute (job finished "
                    "before shard 0 reached version %d)"
                    % kill_at_version
                )
            if not relaunched.wait(timeout=1):
                raise RuntimeError("killed shard was never relaunched")
            status0 = status_clients[0].call("ps_status")
            arm["restored_version"] = int(
                status0.get("restored_version", -1)
            )
            version, state = fleet_state(addrs, probe_ids)
            arm["final_version"] = int(version)
        finally:
            chaos.stop()
            for c in status_clients:
                c.close()
            _stop_ps_fleet(procs)
        events = profiling.events.tail(4096)
        restore_events = [
            e for e in events if e["kind"] == "ps_shard_restore"
        ]
        arm["saw_shard_failure_event"] = any(
            e["kind"] == "ps_shard_failure" for e in events
        )
        arm["saw_shard_restore_event"] = bool(restore_events)
        arm["rollback_depth"] = max(
            [int(e.get("rollback_depth") or 0) for e in restore_events],
            default=-1,
        )
        return arm, state

    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        id_rng = np.random.default_rng(29)
        shuffled = id_rng.permutation(5383)
        early_pool = shuffled[:pool_size]
        late_pool = shuffled[pool_size : 2 * pool_size]
        data = pooled_frappe_file(
            records, tmp, "pool", (early_pool, late_pool)
        )
        warm = pooled_frappe_file(
            batch * 2, tmp, "pool_warm", (early_pool,)
        )
        probe_ids = np.sort(early_pool).astype(np.int64)

        # -- fault-free arm (same snapshot config, no faults) -----------
        procs, addrs, _, _ = _launch_ps_fleet_ex(
            tmp,
            MODEL_ZOO_PATH,
            model_def,
            "chaos-clean",
            extra_args=[
                "--ps_snapshot_versions", str(cadence),
                "--ps_snapshot_dir", os.path.join(tmp, "snap-clean"),
            ],
        )
        try:
            run_job(addrs, warm, batch * 2)
            run_job(addrs, data, records)
            clean_version, clean = fleet_state(addrs, probe_ids)
        finally:
            _stop_ps_fleet(procs)
        results["clean_version"] = int(clean_version)

        # -- chaos arm A: kill + relaunch WITH the snapshot cadence -----
        restored_arm, restored_state = run_chaos_arm(
            "chaos-restored",
            [
                "--ps_snapshot_versions", str(cadence),
                "--ps_snapshot_dir", os.path.join(tmp, "snap-chaos"),
            ],
            data,
            warm,
        )
        results.update(
            {"restored_" + k: v for k, v in restored_arm.items()}
        )

        # -- chaos arm B: the same kill with durability OFF (the
        # pre-recovery-plane hazard this PR removes): the relaunched
        # shard boots empty, the worker re-pushes dense + infos, and
        # every trained embedding row of that shard resets to init ----
        reinit_arm, reinit_state = run_chaos_arm(
            "chaos-reinit", [], data, warm
        )
        results.update({"reinit_" + k: v for k, v in reinit_arm.items()})

        d_restored = float(np.linalg.norm(restored_state - clean))
        d_reinit = float(np.linalg.norm(reinit_state - clean))
        results.update(
            {
                "cadence": cadence,
                "kill_at_version": kill_at_version,
                "l2_restored_vs_clean": d_restored,
                "l2_reinit_vs_clean": d_reinit,
                "divergence_ratio": d_restored / max(d_reinit, 1e-12),
            }
        )

        # ---- master recovery arms (docs/master_recovery.md) -----------
        # the same deepfm fleet, now driven by a REAL master.main OS
        # process with the dispatch journal on: fault-free twice under
        # different task-shuffle seeds (their L2 distance is the
        # ORGANIC task-order noise floor of this async job) and once
        # with a scripted SIGKILL of the master at a journal done-count,
        # relaunched same port + journal dir. The worker runs in this
        # process on the failover channel and must ride the outage out.
        results.update(_master_chaos_arms(tmp, quick))
    return results


def _master_chaos_arms(tmp, quick):
    import socket
    import subprocess
    import threading

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.journal import MasterJournal
    from elasticdl_tpu.master.rpc_service import MasterClient
    from elasticdl_tpu.rpc.core import Client
    from elasticdl_tpu.tools.chaos import ChaosOp, FleetChaos
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    from tests.test_utils import MODEL_ZOO_PATH

    batch = 16
    m_nmpt = 2  # records_per_task = 32: one master round trip per 2 batches
    m_records = 512 if quick else 768
    m_tasks = m_records // (batch * m_nmpt)
    m_kill_at_done = 3
    # pace the job with injected per-RPC RTT on the PS fleet so the
    # scripted kill reliably lands MID-job (an unpaced CPU run drains
    # the whole ledger inside one chaos poll interval)
    m_rtt_ms = 30.0
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"

    # reuse the pooled-id FRAPPE schema (deterministic ids); the master
    # reads shards from a DIRECTORY
    rng = np.random.default_rng(31)
    pool = rng.permutation(5383)[:96]
    probe_ids = np.sort(pool).astype(np.int64)
    mdata_dir = os.path.join(tmp, "mdata")
    os.makedirs(mdata_dir, exist_ok=True)
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter

    with RecordIOWriter(os.path.join(mdata_dir, "m.edlr")) as f:
        for _ in range(m_records):
            f.write(
                encode_example(
                    {
                        "feature": rng.choice(pool, size=(10,)).astype(
                            np.int64
                        ),
                        "label": np.array(
                            [rng.integers(2)], dtype=np.int64
                        ),
                    }
                )
            )

    def fleet_probe(addrs):
        client = PSClient([BoundPS(a, deadline_s=10.0) for a in addrs])
        try:
            ok, version, named = client.pull_dense()
            if not ok:
                raise RuntimeError("fleet reports uninitialized params")
            rows = client.pull_embedding_vectors_multi(
                {name: probe_ids for name in ("embedding", "id_bias")}
            )
        finally:
            client.close()
        parts = [
            np.asarray(named[k], np.float64).ravel()
            for k in sorted(named)
        ] + [
            np.asarray(rows[name], np.float64).ravel()
            for name in ("embedding", "id_bias")
        ]
        return int(version), np.concatenate(parts)

    def _wait_tcp(proc_fn, port, what, timeout=120):
        deadline = time.time() + timeout
        while True:
            proc = proc_fn()
            if proc.poll() is not None:
                raise RuntimeError(
                    "%s exited rc=%s at boot" % (what, proc.returncode)
                )
            try:
                with socket.create_connection(("localhost", port), 1.0):
                    return
            except OSError:
                if time.time() > deadline:
                    raise RuntimeError("%s did not come up" % what)
                time.sleep(0.2)

    def _mstatus(mport, timeout=90):
        """master_status on a FRESH channel per attempt: a channel
        that lived through the SIGKILL can wedge in gRPC's failure
        state long after the relaunched master serves — probe channels
        are disposable (the fleet-test discipline)."""
        import grpc

        deadline = time.time() + timeout
        while True:
            probe = Client("localhost:%d" % mport, deadline_s=5.0)
            try:
                return probe.call("master_status")
            except grpc.RpcError:
                if time.time() >= deadline:
                    raise
                time.sleep(0.3)
            finally:
                probe.close()

    def run_master_arm(tag, seed, kill_at_done=None):
        procs, addrs, _, env = _launch_ps_fleet_ex(
            tmp,
            MODEL_ZOO_PATH,
            model_def,
            tag,
            extra_args=["--rpc_inject_delay_ms", str(m_rtt_ms)],
        )
        s = socket.socket()
        s.bind(("localhost", 0))
        mport = s.getsockname()[1]
        s.close()
        journal_dir = os.path.join(tmp, "journal-" + tag)
        mcmd = [
            sys.executable, "-m", "elasticdl_tpu.master.main",
            "--job_name", "chaos-" + tag,
            "--port", str(mport),
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", str(batch),
            "--num_minibatches_per_task", str(m_nmpt),
            "--num_epochs", "1",
            "--training_data", mdata_dir,
            "--num_workers", "0",
            "--num_ps_pods", "2",
            "--use_async", "true",
            "--grads_to_wait", "1",
            "--master_journal_dir", journal_dir,
            "--master_journal_fsync_ms", "20",
        ]
        menv = dict(env)
        menv.update(
            {
                "EDL_MASTER_POLL_SECS": "1",
                # the dispatcher shuffle is the one entropy source the
                # divergence gate cannot pin from outside the process
                "EDL_TASK_SHUFFLE_SEED": str(seed),
                "JAX_PLATFORMS": "cpu",
            }
        )
        merr = open(os.path.join(tmp, "master-%s.err" % tag), "ab")

        def spawn_master():
            return subprocess.Popen(
                mcmd,
                env=menv,
                stdout=subprocess.DEVNULL,
                stderr=merr,
            )

        box = {"proc": spawn_master()}
        _wait_tcp(lambda: box["proc"], mport, "master " + tag)
        status_client = Client(
            "localhost:%d" % mport,
            deadline_s=2.0,
            retries=3,
            backoff_s=0.3,
        )
        chaos = None
        relaunched = threading.Event()
        arm = {}
        try:
            arm["epoch_initial"] = int(
                _mstatus(mport)["master_epoch"]
            )
            if kill_at_done is not None:

                class _MasterFleet:
                    """kill_master = SIGKILL + relaunch with the same
                    argv/port/journal — the LocalInstanceManager
                    relaunch contract, driven by this arm's own
                    process handle."""

                    def kill_master(self):
                        p = box["proc"]
                        p.kill()
                        p.wait(timeout=10)
                        box["proc"] = spawn_master()
                        relaunched.set()

                    terminate_master = kill_master

                chaos = FleetChaos(
                    _MasterFleet(),
                    lambda shard: {},
                    [ChaosOp("kill_master", -1, at_done=kill_at_done)],
                    poll_s=0.05,
                    master_status_fn=lambda: status_client.call(
                        "master_status"
                    ),
                ).start()
            stub = MasterClient(
                "localhost:%d" % mport, failover_s=240.0
            )
            ps_client = PSClient(
                [
                    BoundPS(
                        a, deadline_s=5.0, retries=2, backoff_s=0.2
                    )
                    for a in addrs
                ],
                hot_row_cache_rows=0,
                push_inflight=0,
            )
            worker = Worker(
                worker_id=1,
                job_type=JobType.TRAINING_ONLY,
                minibatch_size=batch,
                model_zoo=MODEL_ZOO_PATH,
                model_def=model_def,
                model_params=model_params,
                stub=stub,
                ps_client=ps_client,
                seed=7,
                # synchronous acks: the chaos trigger is the journal's
                # done count, so completions must land promptly rather
                # than in boundary-drain bursts
                task_ack_queue=0,
            )
            try:
                worker.run()
                arm["worker_survived"] = True
            finally:
                try:
                    ps_client.close()
                finally:
                    stub.close()
            if chaos is not None:
                chaos.stop()
                if not chaos.done():
                    raise RuntimeError(
                        "master chaos schedule did not execute (job "
                        "finished before %d done tasks)" % kill_at_done
                    )
                if not relaunched.wait(timeout=1):
                    raise RuntimeError(
                        "killed master was never relaunched"
                    )
                arm["kill_trigger_done"] = int(chaos.executed[0][1])
                if arm["kill_trigger_done"] >= m_tasks:
                    raise RuntimeError(
                        "the kill landed after the ledger drained "
                        "(done=%d of %d) — not a mid-job outage; "
                        "raise the RTT pacing"
                        % (arm["kill_trigger_done"], m_tasks)
                    )
            st = _mstatus(mport)
            arm["epoch_final"] = int(st["master_epoch"])
            # the master observes completion through its own poll and
            # exits 0 — the whole point of the relaunch being a real
            # member of the job, not a bystander
            deadline = time.time() + 120
            while (
                box["proc"].poll() is None and time.time() < deadline
            ):
                time.sleep(0.2)
            if box["proc"].poll() != 0:
                raise RuntimeError(
                    "master (%s) did not exit cleanly after "
                    "completion (rc=%r)" % (tag, box["proc"].poll())
                )
            version, state = fleet_probe(addrs)
            arm["final_version"] = version
        finally:
            if chaos is not None:
                chaos.stop()
            status_client.close()
            p = box["proc"]
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    print(
                        "WARN: master (%s) unreaped after SIGKILL" % tag
                    )
            merr.close()
            _stop_ps_fleet(procs)
        jstate = MasterJournal(journal_dir).replay()
        arm["journal"] = dict(jstate.counters)
        arm["journal"]["pending"] = len(jstate.pending)
        return arm, state

    clean_a, state_a = run_master_arm("mclean-a", seed=11)
    clean_b, state_b = run_master_arm("mclean-b", seed=12)
    chaos_arm, state_c = run_master_arm(
        "mchaos", seed=11, kill_at_done=m_kill_at_done
    )
    noise = float(np.linalg.norm(state_a - state_b))
    d_chaos = float(np.linalg.norm(state_c - state_a))
    return {
        "master_expected_tasks": m_tasks,
        "master_kill_at_done": m_kill_at_done,
        "master_clean_journal": clean_a["journal"],
        "master_chaos_journal": chaos_arm["journal"],
        "master_chaos_epoch_initial": chaos_arm["epoch_initial"],
        "master_chaos_epoch_final": chaos_arm["epoch_final"],
        "master_chaos_worker_survived": bool(
            chaos_arm.get("worker_survived")
        ),
        "master_noise_l2": noise,
        "master_chaos_l2": d_chaos,
        "master_divergence_ratio": d_chaos / max(noise, 1e-12),
    }


def bench_hybrid(quick=False):
    """Hybrid comm plane vs the PS-everything trainer
    (docs/embedding_planes.md): the same deepfm workload against the
    same 2-process injected-RTT PS fleet, driven (a) with every
    parameter — dense layers included — round-tripping through the PS
    (the classic loop at its best known config: fan-out + async push
    window + get_model_steps=4) and (b) in hybrid mode, where dense
    parameters live in the local/allreduce world and only the
    PS-plane embedding table is served by the fleet, its per-batch
    pull overlapped behind the previous batch's jitted step. An
    equivalence pre-pass runs first: PS-only and hybrid produce
    BITWISE-identical lookups and dense gradients from a common
    initialization (the SSP window's step-0 point), so the speedup is
    a wire-plane property, not a numerics change. CPU-forced
    subprocess, same containment as --ps."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, json\n"
        "print('HYBENCH ' + json.dumps(bench._bench_hybrid_impl(%r)))\n"
    ) % (here, quick)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=1800,
            cwd=here,
        )
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            "hybrid bench timed out:\n%s" % str(e.stdout or "")[-2000:]
        ) from e
    for line in proc.stdout.splitlines():
        if line.startswith("HYBENCH "):
            return json.loads(line[len("HYBENCH "):])
    raise RuntimeError(
        "hybrid bench failed:\n"
        + proc.stdout[-2000:]
        + proc.stderr[-2000:]
    )


def _hybrid_equivalence_check():
    """The --hybrid pre-pass: PS-only vs hybrid planes from one common
    initialization produce bitwise-identical lookups (forward logits),
    loss, shared dense gradients, and embedding-row gradients (the
    hybrid bias table's dense gradient must equal the PS arm's
    scattered sparse rows). In-process servicers: no wire, no
    scheduling noise — pure plane numerics."""
    import optax

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.ps_client import PSClient
    from tests.test_utils import MODEL_ZOO_PATH
    from elasticdl_tpu.worker.worker import Worker

    vocab, dim = 96, 16
    rng = np.random.default_rng(11)
    pool = rng.permutation(vocab)[:24]
    weights = 1.0 / np.arange(1, 25) ** 1.1
    weights /= weights.sum()
    # power-law duplicated ids: the dedup planner's combined row grads
    # must match the dense scatter under heavy duplication too
    features = {
        "feature": rng.choice(pool, size=(64, 10), p=weights).astype(
            np.int64
        )
    }
    labels = rng.integers(0, 2, size=(64, 1)).astype(np.int32)

    servicers = [
        PserverServicer(
            Parameters(),
            grads_to_wait=1,
            optimizer=optax.sgd(0.1),
            use_async=True,
        )
        for _ in range(2)
    ]

    def make_worker(zoo_plane, worker_plane):
        return Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=64,
            model_zoo=MODEL_ZOO_PATH,
            model_def=(
                "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
            ),
            model_params="embedding_dim=%d,fc_unit=16,vocab_size=%d,"
            "embedding_plane='%s'" % (dim, vocab, zoo_plane),
            ps_client=PSClient(servicers),
            embedding_plane=worker_plane,
            embedding_prefetch=False,
        )

    wp = make_worker("ps", "ps")
    wh = make_worker("hybrid", "hybrid")
    wp._run_model_call_before_training(features)
    wh._run_model_call_before_training(features)
    # one common initialization: shared dense leaves copied across, the
    # hybrid bias table seeded from the SAME store rows the PS arm pulls
    for key in ("Dense_0", "Dense_1"):
        wh._params[key] = wp._params[key]
    bias_rows = wp._ps_client.pull_embedding_vectors(
        "id_bias", np.arange(vocab)
    )
    import jax.numpy as jnp

    wh._params["id_bias"]["table"] = jnp.asarray(
        np.asarray(bias_rows, np.float32)
    )

    checks = {}
    fp = wp.forward_process(features)
    fh = wh.forward_process(features)
    checks["lookups_identical"] = bool(
        np.array_equal(np.asarray(fp["logits"]), np.asarray(fh["logits"]))
    )
    lp, gp, sp = wp.training_process(features, labels)
    lh, gh, sh = wh.training_process(features, labels)
    checks["loss_identical"] = float(lp) == float(lh)
    checks["dense_grads_identical"] = all(
        np.array_equal(np.asarray(gp[k][leaf]), np.asarray(gh[k][leaf]))
        for k in ("Dense_0", "Dense_1")
        for leaf in gp[k]
    )
    sp_by = {t.name: t for t in sp}
    sh_by = {t.name: t for t in sh}
    checks["embedding_row_grads_identical"] = bool(
        np.array_equal(
            sp_by["embedding"].values, sh_by["embedding"].values
        )
        and np.array_equal(
            sp_by["embedding"].indices, sh_by["embedding"].indices
        )
    )
    scattered = np.zeros((vocab, 1), np.float32)
    scattered[np.asarray(sp_by["id_bias"].indices)] = np.asarray(
        sp_by["id_bias"].values
    )
    checks["bias_plane_grads_identical"] = bool(
        np.array_equal(scattered, np.asarray(gh["id_bias"]["table"]))
    )
    for worker in (wp, wh):
        worker._ps_client.close()
    checks["ok"] = all(checks.values())
    return checks


def _bench_hybrid_impl(quick=False):
    import tempfile

    _force_cpu_backend()
    _reap_stale_fleet()

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    results = {"equivalence": _hybrid_equivalence_check()}
    if not results["equivalence"]["ok"]:
        return results

    records = 256 if quick else 2048
    batch = 32
    rtt_ms = 30.0
    results["rtt_ms"] = rtt_ms
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"

    def launch_fleet(tag):
        return _launch_ps_fleet(
            tmp,
            MODEL_ZOO_PATH,
            model_def,
            "hy-" + tag,
            extra_args=["--rpc_inject_delay_ms", str(rtt_ms)],
        )

    stop_fleet = _stop_ps_fleet

    def run_job(addrs, data, n, model_params, worker_kwargs):
        shards = {data: (0, n)}
        task_d = TaskDispatcher(shards, {}, {}, batch * 4, 1)
        master = MasterServicer(
            1,
            batch,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        ps_client = PSClient(
            [BoundPS(a) for a in addrs],
            fanout=True,
            push_inflight=1,
        )
        worker = Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=batch,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
            model_params=model_params,
            ps_client=ps_client,
            **worker_kwargs,
        )
        worker._stub = InProcessMaster(master)
        t0 = time.perf_counter()
        try:
            worker.run()
        finally:
            ps_client.close()
        dt = time.perf_counter() - t0
        if not task_d.finished():
            raise RuntimeError("hybrid bench job did not finish")
        return n / dt

    base_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"
    arms = {
        # the PS-everything baseline at its best known config: fan-out
        # + async push window + SSP local updates between pulls
        "examples_per_sec_ps": (
            base_params + ",embedding_plane='ps'",
            dict(get_model_steps=4),
        ),
        # hybrid: dense local, sparse pull prefetched behind compute,
        # sparse-only pushes through the same async window
        "examples_per_sec_hybrid": (
            base_params + ",embedding_plane='hybrid'",
            dict(embedding_plane="hybrid"),
        ),
    }
    with tempfile.TemporaryDirectory() as tmp_dir:
        tmp = tmp_dir
        f = create_recordio_file(
            records, DatasetName.FRAPPE, 10, temp_dir=tmp
        )
        warm = create_recordio_file(
            batch * 4, DatasetName.FRAPPE, 10, temp_dir=tmp
        )
        # fresh fleet per arm: each pays its own lazy table init and
        # sees untouched versions; the warmup job pays worker jit
        # compiles (first arm) and the fleet's lazy init (every arm)
        for key, (model_params, worker_kwargs) in arms.items():
            procs, addrs = launch_fleet(key[-6:])
            try:
                run_job(addrs, warm, batch * 4, model_params, worker_kwargs)
                results[key] = run_job(
                    addrs, f, records, model_params, worker_kwargs
                )
            finally:
                stop_fleet(procs)
    return results


def _scorer_boot_code():
    """Scorer-pod bootstrap: CPU-forced + parent-death watchdog (the
    same discipline as _ps_fleet_boot_code, marker included so the
    stale-fleet reaper covers scorers too)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return (
        "import os, sys, threading, time\n"
        "sys.path.insert(0, %r)\n"
        "import bench\n"
        "bench._force_cpu_backend()\n"
        "_parent = os.getppid()\n"
        "def _watch():\n"
        "    while os.getppid() == _parent:\n"
        "        time.sleep(1.0)\n"
        "    os._exit(0)\n"
        "threading.Thread(target=_watch, daemon=True).start()\n"
        "from elasticdl_tpu.serving.main import main\n"
        "sys.exit(main())\n"
    ) % here


def _serve_batch_arms(addrs, export_root, staleness_window, pool,
                      weights, quick):
    """Micro-batching arms (docs/serving.md "Micro-batching"): an
    in-process scorer over the live PS fleet runs (1) a bitwise
    equivalence pre-pass (coalesced+repeat-row-padded forward vs
    scoring each request alone), (2) a closed-loop max-QPS A/B —
    one-request-per-forward vs MicroBatcher.submit from the same
    driver pool, and (3) an open-loop bursty arm with scheduled
    arrivals: a base rate the plane absorbs, a burst past capacity
    that admission control must shed, and a shed-rate-outside-burst
    measurement. All three are gated rc-1 in main."""
    import threading

    from elasticdl_tpu.serving.batcher import MicroBatcher, Overloaded
    from elasticdl_tpu.serving.scorer import (
        ModelDirectoryWatcher,
        Scorer,
    )
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient

    rows_per_req = 4

    def small_req(drng):
        return {
            "feature": drng.choice(
                pool, size=(rows_per_req, 10), p=weights
            ).astype(np.int64)
        }

    client = PSClient(
        [BoundPS(a, deadline_s=20.0, retries=3) for a in addrs]
    )
    scorer = Scorer(
        ps_client=client, staleness_versions=staleness_window
    )
    # SLO aligned with the bench p99 gate: predicted queue wait past
    # ~2 s sheds. The deliberately small 64-row cap is what sheds the
    # bursty arm's past-capacity window — a 2-batch backlog bound, so
    # admitted requests clear fast and sheds stop with the burst.
    batcher = MicroBatcher(
        scorer,
        max_batch=32,
        timeout_ms=2.0,
        p99_slo_ms=2000.0,
        queue_rows=64,
    )
    out = {}
    try:
        scorer.set_warm_batch_sizes(batcher.buckets)
        watcher = ModelDirectoryWatcher(export_root, scorer)
        if watcher.poll_once() is None:
            raise RuntimeError(
                "A/B scorer found no complete export under %s"
                % export_root
            )
        batcher.start()

        # -- (1) bitwise equivalence pre-pass ----------------------
        rng = np.random.default_rng(77)
        eq_ok = True
        for n in (3, 4, 5, 6):  # 3 and 5 pad up to the 4/8 buckets
            feats = {
                "feature": rng.choice(
                    pool, size=(n, 10), p=weights
                ).astype(np.int64)
            }
            ref, _v = scorer.score(feats)
            got, _v2 = batcher.submit(feats)
            ref = ref if isinstance(ref, dict) else {"out": ref}
            got = got if isinstance(got, dict) else {"out": got}
            for key in ref:
                if not np.array_equal(
                    np.asarray(ref[key]), np.asarray(got[key])
                ):
                    eq_ok = False
        out["equivalence_ok"] = eq_ok

        # -- (2) closed-loop A/B: solo forwards vs coalesced -------
        ab_threads = 8
        ab_secs = 2.0 if quick else 4.0

        def run_arm(call, name):
            stop = threading.Event()
            counts = [0] * ab_threads
            errs = []

            def loop(i):
                drng = np.random.default_rng(500 + i)
                while not stop.is_set():
                    feats = small_req(drng)
                    try:
                        call(feats)
                    except Exception as err:  # noqa: BLE001
                        errs.append(err)
                        return
                    counts[i] += 1

            ts = [
                threading.Thread(
                    target=loop, args=(i,), daemon=True,
                    name="serve-ab-%s-%d" % (name, i),
                )
                for i in range(ab_threads)
            ]
            t0 = time.monotonic()
            for t in ts:
                t.start()
            time.sleep(ab_secs)
            stop.set()
            for t in ts:
                t.join(timeout=60)
            if errs:
                raise errs[0]
            done = sum(counts)
            return done / max(1e-9, time.monotonic() - t0), done

        unbatched_qps, _ = run_arm(
            lambda f: scorer.score(f), "solo"
        )
        forwards_before = batcher._c_batches.value()
        batched_qps, batched_reqs = run_arm(
            lambda f: batcher.submit(f), "coalesced"
        )
        forwards = batcher._c_batches.value() - forwards_before
        out["unbatched_qps"] = unbatched_qps
        out["batched_qps"] = batched_qps
        out["batch_speedup"] = batched_qps / max(1e-9, unbatched_qps)
        out["batched_rows_per_forward"] = (
            batched_reqs * rows_per_req / max(1, forwards)
        )

        # -- (3) open-loop bursty arm ------------------------------
        base_s = 1.5 if quick else 3.0
        burst_s = 1.0
        # closed-loop capacity rides 8-deep coalescing; open-loop base
        # arrivals coalesce barely at all (1-2 requests per forward),
        # so the absorbable base rate is a fraction of batched_qps —
        # 12% keeps the single dispatcher at comfortable utilization
        base_qps = max(20.0, min(0.12 * batched_qps, 80.0))
        burst_qps = min(
            max(2.0 * batched_qps, 8.0 * base_qps), 1200.0
        )
        arrivals = []  # (t_rel, in_burst_window)
        for phase_t0, phase_s, qps in (
            (0.0, base_s, base_qps),
            (base_s, burst_s, burst_qps),
            (base_s + burst_s, base_s, base_qps),
        ):
            n = int(phase_s * qps)
            for k in range(n):
                t_rel = phase_t0 + k / qps
                # the post-burst drain tail still counts as "burst"
                # for the shed-outside gate: sheds there are the
                # queue emptying, not steady-state overload
                in_burst = (
                    base_s - 0.05
                    <= t_rel
                    <= base_s + burst_s + 0.5
                )
                arrivals.append((t_rel, in_burst))
        arrivals.sort(key=lambda a: a[0])

        idx = [0]
        idx_mu = threading.Lock()
        rec = []  # (in_burst, status, dt)
        rec_mu = threading.Lock()
        t0 = time.monotonic()

        def issuer(k):
            drng = np.random.default_rng(900 + k)
            while True:
                with idx_mu:
                    if idx[0] >= len(arrivals):
                        return
                    j = idx[0]
                    idx[0] += 1
                t_rel, in_burst = arrivals[j]
                delay = t0 + t_rel - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                # a request the pool issued late — inside the burst's
                # ACTUAL window or its 0.5 s drain tail — is burst
                # traffic no matter when the schedule wanted it
                t_iss = time.monotonic() - t0
                in_burst = in_burst or (
                    base_s - 0.05 <= t_iss <= base_s + burst_s + 0.5
                )
                feats = small_req(drng)
                ts = time.perf_counter()
                try:
                    batcher.submit(feats)
                    status = "ok"
                except Overloaded:
                    status = "shed"
                except Exception:  # noqa: BLE001 — counted + gated
                    status = "error"
                dt = time.perf_counter() - ts
                with rec_mu:
                    rec.append((in_burst, status, dt))

        # the pool must HOLD the open-loop schedule through the burst
        # (offered x in-flight latency, with headroom) — a starved
        # pool re-issues the burst's backlog after it ends and turns
        # scheduled base traffic into a compressed storm
        issuers = [
            threading.Thread(
                target=issuer, args=(k,), daemon=True,
                name="serve-bursty-%d" % k,
            )
            for k in range(192)
        ]
        for t in issuers:
            t.start()
        for t in issuers:
            t.join(timeout=120)
        oks = [r for r in rec if r[1] == "ok"]
        lat = sorted(r[2] for r in oks)
        outside = [r for r in rec if not r[0]]
        shed_outside = sum(1 for r in outside if r[1] == "shed")
        out["bursty"] = {
            "base_qps_offered": base_qps,
            "burst_qps_offered": burst_qps,
            "requests": len(rec),
            "ok": len(oks),
            "errors": sum(1 for r in rec if r[1] == "error"),
            "shed_in_burst": sum(
                1 for r in rec if r[0] and r[1] == "shed"
            ),
            "shed_outside_burst": shed_outside,
            "n_outside": len(outside),
            "shed_rate_outside": (
                shed_outside / max(1, len(outside))
            ),
            "ok_qps": len(oks) / (2 * base_s + burst_s),
            "p99_ms": (
                1e3 * lat[min(len(lat) - 1, int(0.99 * len(lat)))]
                if lat
                else -1.0
            ),
        }
    finally:
        batcher.stop(drain=True)
        batcher.close()
        scorer.close()
        client.close()
    return out


def bench_serve(quick=False):
    """The serving plane's gate (docs/serving.md): a 2-process scorer
    fleet answering sustained score traffic from the live export
    stream + PS-resident embeddings WHILE an in-process streaming
    trainer churns versions, with a mid-bench PS shard SIGKILL +
    relaunch, THEN the micro-batching arms (_serve_batch_arms):
    bitwise batched-vs-unbatched equivalence, a coalesced-vs-solo
    max-QPS A/B, and an open-loop bursty arm exercising SLO admission
    control. Gated (explicit rc-1 in main): p99 latency, the
    staleness bound (no served row older than the configured window,
    scraped via each scorer's /metrics), at least one hot swap under
    churn, post-recovery health, batched >= the speedup gate x solo,
    and shed-rate ~0 outside the burst."""
    return _bench_serve_impl(quick)


def _bench_serve_impl(quick=False):
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.request

    _force_cpu_backend()
    _reap_stale_fleet()

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.rpc.core import Client
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import MODEL_ZOO_PATH

    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=16,fc_unit=16,vocab_size=5383"
    batch = 32
    staleness_window = 4
    export_every = 8
    n_scorers = 2
    drive_s = 15.0 if quick else 40.0
    snapshot_every = 2

    def powerlaw_batch(rng, pool, weights, n=batch):
        return {
            "feature": rng.choice(pool, size=(n, 10), p=weights).astype(
                np.int64
            )
        }

    def powerlaw_file(n, tmp, rng, pool, weights):
        from elasticdl_tpu.data.example import encode_example
        from elasticdl_tpu.data.recordio import RecordIOWriter

        path = os.path.join(tmp, "serve_powerlaw_%d.edlr" % n)
        with RecordIOWriter(path) as f:
            for _ in range(n):
                f.write(
                    encode_example(
                        {
                            "feature": rng.choice(
                                pool, size=(10,), p=weights
                            ).astype(np.int64),
                            "label": np.array(
                                [rng.integers(2)], dtype=np.int64
                            ),
                        }
                    )
                )
        return path

    def scrape_metrics(port):
        with urllib.request.urlopen(
            "http://localhost:%d/metrics" % port, timeout=10
        ) as resp:
            text = resp.read().decode("utf-8")
        out = {}
        for line in text.splitlines():
            if line.startswith("#") or " " not in line:
                continue
            name, _, value = line.rpartition(" ")
            try:
                out[name] = float(value)
            except ValueError:
                continue
        return out

    rng = np.random.default_rng(11)
    pool = rng.permutation(5383)[:64]
    weights = 1.0 / np.arange(1, 65) ** 1.1
    weights /= weights.sum()

    results = {
        "staleness_window": staleness_window,
        "n_scorers": n_scorers,
    }
    with tempfile.TemporaryDirectory() as tmp:
        data = powerlaw_file(batch * 8, tmp, rng, pool, weights)
        export_root = os.path.join(tmp, "exports")
        os.makedirs(export_root)
        snap_dir = os.path.join(tmp, "snap")
        procs, addrs, cmds, env = _launch_ps_fleet_ex(
            tmp,
            MODEL_ZOO_PATH,
            model_def,
            "serve",
            extra_args=[
                "--ps_snapshot_versions", str(snapshot_every),
                "--ps_snapshot_dir", snap_dir,
            ],
        )
        scorer_procs = []
        clients = []
        ps_client = None
        task_d = None
        stop_drive = threading.Event()
        trainer_done = threading.Event()
        trainer_err = []
        try:
            # -- the streaming trainer (in-process thread) --------------
            task_d = TaskDispatcher(
                {data: (0, batch * 8)}, {}, {}, batch * 2, 1,
                streaming=True,
            )
            master = MasterServicer(
                1,
                batch,
                None,
                task_d,
                checkpoint_service=CheckpointService("", 0, 0, False),
                use_async=True,
            )
            ps_client = PSClient(
                [BoundPS(a, deadline_s=20.0, retries=3) for a in addrs]
            )
            worker = Worker(
                worker_id=1,
                job_type=JobType.TRAINING_ONLY,
                minibatch_size=batch,
                model_zoo=MODEL_ZOO_PATH,
                model_def=model_def,
                model_params=model_params,
                ps_client=ps_client,
                get_model_steps=4,
                export_dir=export_root,
                export_every_versions=export_every,
                export_keep=4,
            )
            worker._stub = InProcessMaster(master)

            def train():
                try:
                    worker.run()
                except Exception as err:  # noqa: BLE001 — surfaced below
                    trainer_err.append(err)
                finally:
                    trainer_done.set()

            t_train = threading.Thread(
                target=train, daemon=True, name="serve-trainer"
            )
            t_train.start()

            # -- the scorer fleet (real OS processes) -------------------
            ports, tports = [], []
            for _ in range(n_scorers):
                for bucket in (ports, tports):
                    s = socket.socket()
                    s.bind(("localhost", 0))
                    bucket.append(s.getsockname()[1])
                    s.close()
            boot = _scorer_boot_code()
            for i in range(n_scorers):
                err = open(
                    os.path.join(tmp, "scorer-%d.err" % i), "ab"
                )
                scorer_procs.append(
                    (
                        subprocess.Popen(
                            [
                                sys.executable, "-c", boot,
                                "--scorer_id", str(i),
                                "--export_dir", export_root,
                                "--ps_addrs", ",".join(addrs),
                                "--port", str(ports[i]),
                                "--scorer_telemetry_port",
                                str(tports[i]),
                                "--serving_staleness_versions",
                                str(staleness_window),
                                "--serving_sync_interval_s", "0.25",
                                "--watch_interval_s", "0.5",
                                # micro-batching ON for the whole
                                # drive: the SIGKILL drill must stay
                                # green THROUGH the coalescing path
                                "--serve_max_batch", "64",
                                "--serve_batch_timeout_ms", "2",
                                "--serve_p99_slo_ms", "2000",
                            ],
                            env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=err,
                        ),
                        err,
                    )
                )
            clients = [
                Client("localhost:%d" % p, deadline_s=60.0)
                for p in ports
            ]
            # scorers answer status immediately; score needs the
            # trainer's FIRST export (worker jit + export cadence)
            deadline = time.time() + 420
            first_versions = []
            for i, client in enumerate(clients):
                while True:
                    if trainer_err:
                        raise trainer_err[0]
                    proc, errf = scorer_procs[i]
                    if proc.poll() is not None:
                        errf.flush()
                        raise RuntimeError(
                            "scorer %d exited rc=%d at boot: %s"
                            % (
                                i,
                                proc.returncode,
                                open(errf.name, "rb").read()[-1500:],
                            )
                        )
                    import grpc

                    try:
                        status = client.call("scorer_status")
                        if int(status.get("model_version", -1)) >= 0:
                            first_versions.append(
                                int(status["model_version"])
                            )
                            break
                    except grpc.RpcError:
                        pass  # still booting: the deadline bounds this
                    if time.time() > deadline:
                        raise RuntimeError(
                            "scorer %d never loaded a model (no "
                            "export arrived?)" % i
                        )
                    time.sleep(0.5)

            # -- warm the request path (first request pays the jit) ----
            for client in clients:
                for _ in range(3):
                    reply = client.call(
                        "score", **powerlaw_batch(rng, pool, weights)
                    )
                    if "error" in reply:
                        raise RuntimeError(
                            "warm score failed: %s" % reply["error"]
                        )

            # -- sustained drive + mid-bench shard kill ----------------
            records = []  # (t_mono, ok, latency_s)
            records_mu = threading.Lock()

            def drive(idx):
                drng = np.random.default_rng(100 + idx)
                client = clients[idx]
                while not stop_drive.is_set():
                    feats = powerlaw_batch(drng, pool, weights)
                    # record the request's START: a request ISSUED
                    # during the outage may return its failure long
                    # after recovery (the scorer's deadline+retry
                    # budget), and classifying by completion would
                    # blame a healthy post-recovery plane for it
                    t_issued = time.monotonic()
                    t0 = time.perf_counter()
                    try:
                        reply = client.call("score", **feats)
                        ok = "error" not in reply
                    except Exception:  # noqa: BLE001 — outage window
                        ok = False
                    dt = time.perf_counter() - t0
                    with records_mu:
                        records.append((t_issued, ok, dt))

            drivers = [
                threading.Thread(
                    target=drive, args=(i,), daemon=True,
                    name="serve-drive-%d" % i,
                )
                for i in range(n_scorers)
            ]
            t_start = time.monotonic()
            for d in drivers:
                d.start()
            # SIGKILL shard 0 mid-drive, relaunch same argv/port (the
            # LocalInstanceManager contract) — snapshots restore it
            time.sleep(drive_s * 0.4)
            kill_t = time.monotonic()
            proc0, err0 = procs[0]
            proc0.kill()
            proc0.wait(timeout=10)
            time.sleep(1.0)
            procs[0] = (
                subprocess.Popen(
                    cmds[0],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=err0,
                ),
                err0,
            )
            port0 = int(addrs[0].rsplit(":", 1)[1])
            _wait_ps_port(procs[0][0], err0, port0, time.time() + 90)
            recovered_t = time.monotonic()
            time.sleep(max(0.0, drive_s - (time.monotonic() - t_start)))
            stop_drive.set()
            for d in drivers:
                d.join(timeout=30)

            # -- post-drive probes -------------------------------------
            final_versions, staleness, hit_rates = [], [], []
            post_ok = 0
            for i, client in enumerate(clients):
                reply = client.call(
                    "score", **powerlaw_batch(rng, pool, weights)
                )
                if "error" not in reply:
                    post_ok += 1
                status = client.call("scorer_status")
                final_versions.append(
                    int(status.get("model_version", -1))
                )
                metrics = scrape_metrics(tports[i])
                staleness.append(
                    metrics.get(
                        "edl_scorer_row_staleness_versions", -1.0
                    )
                )
                hit_rates.append(
                    metrics.get("edl_scorer_hot_row_hit_rate", 0.0)
                )

            # -- wind the stream down ----------------------------------
            task_d.set_streaming(False)
            if not trainer_done.wait(timeout=300):
                raise RuntimeError(
                    "streaming trainer did not drain after "
                    "set_streaming(False)"
                )
            if trainer_err:
                raise trainer_err[0]

            with records_mu:
                done = list(records)
            oks = [r for r in done if r[1]]
            lat = sorted(r[2] for r in oks)
            outage_grace = (recovered_t - kill_t) + 5.0
            bad_outside = [
                r
                for r in done
                if not r[1]
                and not (kill_t - 1.0 <= r[0] <= kill_t + outage_grace)
            ]
            measured_s = max(
                1e-9,
                (max(r[0] for r in done) - t_start) if done else 0.0,
            )
            results.update(
                {
                    "qps": len(oks) / measured_s,
                    "requests_ok": len(oks),
                    "requests_failed": len(done) - len(oks),
                    "failures_outside_outage": len(bad_outside),
                    "p50_ms": 1e3 * lat[len(lat) // 2] if lat else -1.0,
                    "p99_ms": (
                        1e3 * lat[min(len(lat) - 1, int(0.99 * len(lat)))]
                        if lat
                        else -1.0
                    ),
                    "first_versions": first_versions,
                    "final_versions": final_versions,
                    "staleness": staleness,
                    "hit_rates": hit_rates,
                    "post_recovery_scores_ok": post_ok,
                    "outage_s": recovered_t - kill_t,
                    "drive_s": drive_s,
                }
            )

            # -- micro-batching A/B + bursty admission (docs/serving.md,
            # PR-18): in-process scorer against the SAME live PS fleet
            # and newest export, so the arms isolate the batcher itself
            # (no gRPC front door, no training churn — trainer drained
            # above). Small 4-row requests make per-forward host
            # overhead (jit dispatch + embedding plan/pull RTT)
            # dominate: exactly the regime coalescing exists for.
            results.update(
                _serve_batch_arms(
                    addrs, export_root, staleness_window, pool,
                    weights, quick,
                )
            )
        finally:
            stop_drive.set()
            if task_d is not None:
                task_d.set_streaming(False)
            for client in clients:
                try:
                    client.close()
                except Exception as err:  # noqa: BLE001 — teardown
                    print(
                        "scorer client close failed: %s" % err,
                        file=sys.stderr,
                    )
            for proc, err in scorer_procs:
                proc.terminate()
            for proc, err in scorer_procs:
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — teardown
                    proc.kill()
                err.close()
            trainer_done.wait(timeout=60)
            if ps_client is not None:
                ps_client.close()
            _stop_ps_fleet(procs)
    return results


def bench_wire(quick=False):
    """Seed-codec vs scatter-gather vs shared-memory arms on the
    co-located dense pull+push round (docs/wire.md).

    All three arms drive the SAME logical PS round — pull the dense
    params, push a same-shaped gradient — against a real loopback gRPC
    server, the deployment shape of a PS pod co-located with its
    worker. The seed arm replicates the pre-PR-8 copy chain verbatim
    on both sides (ascontiguousarray + tobytes + per-frame joins on
    encode; bytes(view) per segment + values/indices .copy() on
    decode). The scatter-gather arm is the shipped bytes path
    (rpc/core plan + one preallocation + read-only view decode, with
    the PSClient's audited materialize on retained params). The shm
    arm adds the negotiated shared-memory ring, so the gRPC message
    carries ~100 bytes regardless of payload. An equivalence pre-pass
    pins identical pulled params and identical server-observed push
    sums across arms; a bf16 A/B on the scatter-gather arm re-runs the
    r5 experiment that LOST at 0.82x on loopback when compression paid
    its own astype pass — the fused downcast must put it back >=1.0x.
    """
    import struct

    from elasticdl_tpu.common.dtypes import (
        dtype_name_to_numpy,
        dtype_numpy_to_name,
    )
    from elasticdl_tpu.common.tensor import (
        _MAGIC,
        _VERSION,
        Tensor,
        release_message,
    )
    from elasticdl_tpu.rpc.core import Client, serve
    from elasticdl_tpu.rpc.shm_transport import (
        ShmChannel,
        install_shm_endpoint,
    )
    from elasticdl_tpu.rpc.wire_compression import (
        compress_tensors,
        decompress_tensors,
    )

    n_tensors = 8
    n_elems = (64 << 10) if quick else (128 << 10)  # per tensor, f32
    measure_s = 0.8 if quick else 2.0
    rng = np.random.default_rng(8)
    params = [
        Tensor("dense_%d" % i, rng.standard_normal(n_elems).astype(np.float32))
        for i in range(n_tensors)
    ]
    grads = [
        Tensor(t.name, (t.values * 0.01).astype(np.float32)) for t in params
    ]

    # -- the seed codec, replicated verbatim (the chain PR 8 removed) --

    def seed_serialize_tensor(t):
        values = np.ascontiguousarray(t.values)
        header = {
            "name": t.name,
            "dtype": dtype_numpy_to_name(values.dtype),
            "shape": list(values.shape),
        }
        parts = [values.tobytes()]
        if t.indices is not None:
            idx = np.ascontiguousarray(t.indices, dtype=np.int64)
            header["num_indices"] = int(idx.shape[0])
            parts.append(idx.tobytes())
        hdr = json.dumps(header).encode("utf-8")
        return b"".join(
            [_MAGIC, struct.pack("<BI", _VERSION, len(hdr)), hdr] + parts
        )

    def seed_deserialize_tensor(data):
        view = memoryview(data)
        ver, hlen = struct.unpack_from("<BI", view, 4)
        off = 9
        header = json.loads(bytes(view[off : off + hlen]).decode("utf-8"))
        off += hlen
        dtype = dtype_name_to_numpy(header["dtype"])
        shape = tuple(header["shape"])
        n = int(np.prod(shape)) if shape else 1
        values = np.frombuffer(
            view[off : off + n * dtype.itemsize], dtype=dtype
        ).reshape(shape)
        off += n * dtype.itemsize
        indices = None
        if "num_indices" in header:
            k = header["num_indices"]
            indices = np.frombuffer(
                view[off : off + 8 * k], dtype=np.int64
            ).copy()
        return Tensor(header["name"], values.copy(), indices)

    def seed_pack_message(msg):
        header = {}
        segments = []

        def add_segment(data):
            segments.append(data)
            return len(segments) - 1

        for key, value in msg.items():
            if isinstance(value, Tensor):
                header[key] = {
                    "t": "tensor",
                    "i": add_segment(seed_serialize_tensor(value)),
                }
            elif isinstance(value, np.ndarray):
                header[key] = {
                    "t": "array",
                    "i": add_segment(
                        seed_serialize_tensor(Tensor(key, value))
                    ),
                }
            elif (
                isinstance(value, (list, tuple))
                and value
                and isinstance(value[0], Tensor)
            ):
                header[key] = {
                    "t": "tensors",
                    "i": [
                        add_segment(seed_serialize_tensor(t)) for t in value
                    ],
                }
            elif isinstance(value, (bytes, bytearray)):
                header[key] = {"t": "bytes", "i": add_segment(bytes(value))}
            else:
                header[key] = {"t": "json", "v": value}
        hdr = json.dumps(header).encode("utf-8")
        out = [
            struct.pack("<I", len(hdr)),
            hdr,
            struct.pack("<I", len(segments)),
        ]
        for seg in segments:
            out.append(struct.pack("<Q", len(seg)))
            out.append(seg)
        return b"".join(out)

    def seed_unpack_message(data):
        view = memoryview(data)
        (hlen,) = struct.unpack_from("<I", view, 0)
        header = json.loads(bytes(view[4 : 4 + hlen]).decode("utf-8"))
        off = 4 + hlen
        (nseg,) = struct.unpack_from("<I", view, off)
        off += 4
        segments = []
        for _ in range(nseg):
            (slen,) = struct.unpack_from("<Q", view, off)
            off += 8
            segments.append(bytes(view[off : off + slen]))
            off += slen
        msg = {}
        for key, spec in header.items():
            kind = spec["t"]
            if kind == "json":
                msg[key] = spec["v"]
            elif kind == "bytes":
                msg[key] = segments[spec["i"]]
            elif kind in ("tensor", "array"):
                msg[key] = seed_deserialize_tensor(segments[spec["i"]])
            else:
                msg[key] = [
                    seed_deserialize_tensor(segments[i]) for i in spec["i"]
                ]
        return msg

    def serve_seed_codec(methods, port=0):
        """rpc/core.serve with the seed codec on the server side (the
        handler shape mirrors rpc/core._GenericHandler)."""
        import grpc
        from concurrent import futures as _futures

        from elasticdl_tpu.common.constants import GRPC

        class _Handler:
            def service(self, details):
                name = details.method.rsplit("/", 1)[-1]
                fn = methods.get(name)
                if fn is None:
                    return None

                def handler(request_bytes, context):
                    reply = fn(seed_unpack_message(request_bytes))
                    return seed_pack_message(
                        reply if reply is not None else {}
                    )

                return grpc.unary_unary_rpc_method_handler(
                    handler,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        server = grpc.server(
            _futures.ThreadPoolExecutor(max_workers=8),
            options=[
                (
                    "grpc.max_send_message_length",
                    GRPC.MAX_SEND_MESSAGE_LENGTH,
                ),
                (
                    "grpc.max_receive_message_length",
                    GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
                ),
            ],
            handlers=(_Handler(),),
        )
        server._edl_port = server.add_insecure_port("[::]:%d" % port)
        server.start()
        return server

    # -- the shared PS round (what every arm must do) -------------------

    def make_methods(observed, wire_dtype=None):
        """{pull_dense, push_gradient} over ``params``; every push's
        gradient sum lands in ``observed`` for the equivalence pass."""

        def pull_dense(req):
            out, names = compress_tensors(params, wire_dtype)
            return {
                "model_init_status": True,
                "version": 1,
                "params": out,
                "compressed_f32": names,
            }

        def push_gradient(req):
            tensors = decompress_tensors(
                req["gradients"], req.get("compressed_f32")
            )
            observed.append(float(sum(t.values.sum() for t in tensors)))
            return {"accepted": True, "version": 1}

        return {"pull_dense": pull_dense, "push_gradient": push_gradient}

    def pull_round(call, wire_dtype=None):
        """One pull+push round through ``call(method, **fields)``,
        consuming like PSClient does: retained params materialize, the
        message releases (slot recycle on the shm arm)."""
        resp = call("pull_dense")
        named = {}
        for t in decompress_tensors(
            resp["params"], resp.get("compressed_f32")
        ):
            named[t.name] = t.materialize().values
        release_message(resp)
        out, names = compress_tensors(grads, wire_dtype)
        resp = call(
            "push_gradient", gradients=out, compressed_f32=names or None
        )
        release_message(resp)
        return named

    def timed(fn):
        fn()  # warmup: channels connect, pools spin up
        t0 = time.perf_counter()
        rounds = 0
        while time.perf_counter() - t0 < measure_s:
            fn()
            rounds += 1
        return rounds / (time.perf_counter() - t0)

    results = {}
    pulls = {}
    sums = {}

    # seed arm: the replicated copy chain on BOTH sides
    observed = []
    server = serve_seed_codec(make_methods(observed))
    import grpc

    from elasticdl_tpu.common.constants import GRPC

    channel = grpc.insecure_channel(
        "localhost:%d" % server._edl_port,
        options=[
            ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
            (
                "grpc.max_receive_message_length",
                GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
            ),
        ],
    )
    try:
        stub = {}

        def seed_call(method, **fields):
            fn = stub.get(method)
            if fn is None:
                fn = stub[method] = channel.unary_unary(
                    "/elasticdl/%s" % method,
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
            return seed_unpack_message(fn(seed_pack_message(fields)))

        pulls["seed"] = pull_round(seed_call)
        results["seed"] = timed(lambda: pull_round(seed_call))
        sums["seed"] = observed[-1]
    finally:
        channel.close()
        server.stop(None)

    # scatter-gather + shm arms share one server (the shm endpoint
    # costs nothing until a client negotiates)
    observed = []
    methods, registry = install_shm_endpoint(make_methods(observed))
    server = serve(methods, 0)
    sg_client = Client("localhost:%d" % server._edl_port)
    shm_client = Client("localhost:%d" % server._edl_port)
    chan = ShmChannel(shm_client, n_slots=4, slot_mb=8)
    try:
        def sg_call(method, **fields):
            return sg_client.call(method, _retriable=False, **fields)

        pulls["sg"] = pull_round(sg_call)
        results["sg"] = timed(lambda: pull_round(sg_call))
        sums["sg"] = observed[-1]

        pulls["shm"] = pull_round(chan.call)
        results["shm"] = timed(lambda: pull_round(chan.call))
        sums["shm"] = observed[-1]
        if chan.state != "on" or not chan.stats["shm"]:
            raise RuntimeError(
                "shm arm never negotiated (state=%s stats=%s) — the "
                "co-located measurement would silently re-run the "
                "bytes path" % (chan.state, chan.stats)
            )

        # bf16 wire A/B on the scatter-gather arm (the r5 re-run): the
        # downcast now fuses into the frame write, the payload halves
        observed_bf16 = []
        methods_bf16, _reg2 = install_shm_endpoint(
            make_methods(observed_bf16, wire_dtype="bfloat16")
        )
        server_bf16 = serve(methods_bf16, 0)
        bf16_client = Client("localhost:%d" % server_bf16._edl_port)
        try:
            def bf16_round():
                return pull_round(
                    lambda m, **f: bf16_client.call(
                        m, _retriable=False, **f
                    ),
                    wire_dtype="bfloat16",
                )

            named = bf16_round()
            for t in params:  # bf16 tolerance, not byte equality
                np.testing.assert_allclose(
                    named[t.name], t.values, rtol=1e-2, atol=1e-2
                )
            results["sg_bf16"] = timed(bf16_round)
        finally:
            bf16_client.close()
            server_bf16.stop(None)
            _reg2.close()
    finally:
        chan.close()
        shm_client.close()
        sg_client.close()
        server.stop(None)
        registry.close()

    # equivalence pre-pass verdict: identical pulled params, identical
    # server-observed push sums, across all three codec arms
    for arm in ("sg", "shm"):
        for t in params:
            np.testing.assert_array_equal(pulls[arm][t.name], t.values)
            np.testing.assert_array_equal(
                pulls[arm][t.name], pulls["seed"][t.name]
            )
        if abs(sums[arm] - sums["seed"]) > 1e-6 * abs(sums["seed"]):
            raise RuntimeError(
                "push equivalence failed: %s=%r seed=%r"
                % (arm, sums[arm], sums["seed"])
            )
    results["payload_mb"] = n_tensors * n_elems * 4 / (1 << 20)

    # -- device-array arm: host-staged vs dlpack frame ------------------
    # The dlpack bridge (docs/wire.md): a jax.Array frames directly,
    # its single host copy fused into the frame write. The host-staged
    # twin is the pre-bridge get_host_state-then-frame shape — an OWNED
    # host materialization (np.asarray alone returns a view of the
    # device buffer on CPU, which a donating step can recycle under the
    # retained frame source, so the correct staging copies) followed by
    # the frame write: two full-payload passes against the bridge's
    # one. Measured on the co-located shm dense round, where the frame
    # copy IS most of the round; 8 MiB/direction keeps the A/B out of
    # cache-resident noise.
    import jax.numpy as jnp

    dev_elems = 256 << 10
    dev_params = [
        Tensor(
            "dev_%d" % i,
            rng.standard_normal(dev_elems).astype(np.float32),
        )
        for i in range(n_tensors)
    ]
    dev_grads = [
        jnp.asarray((t.values * 0.01).astype(np.float32))
        for t in dev_params
    ]
    observed_dev = []
    methods_dev, reg_dev = install_shm_endpoint(
        {
            "pull_dense": lambda req: {
                "version": 1,
                "params": compress_tensors(dev_params, None)[0],
            },
            "push_gradient": lambda req: (
                observed_dev.append(
                    float(
                        sum(
                            t.values.sum()
                            for t in decompress_tensors(
                                req["gradients"], None
                            )
                        )
                    )
                ),
                {"accepted": True},
            )[1],
        }
    )
    server_dev = serve(methods_dev, 0)
    dev_client = Client("localhost:%d" % server_dev._edl_port)
    dev_chan = ShmChannel(dev_client, n_slots=4, slot_mb=48)
    try:

        def dev_round(grads_of):
            resp = dev_chan.call("pull_dense")
            named = {}
            for t in decompress_tensors(resp["params"], None):
                named[t.name] = t.materialize().values
            release_message(resp)
            resp = dev_chan.call("push_gradient", gradients=grads_of())
            release_message(resp)
            return named

        def host_staged():
            return [
                Tensor(t.name, np.array(np.asarray(g), copy=True))
                for t, g in zip(dev_params, dev_grads)
            ]

        def dlpack_direct():
            return [
                Tensor(t.name, g)
                for t, g in zip(dev_params, dev_grads)
            ]

        # equivalence: both arms land the identical push sum
        dev_round(host_staged)
        dev_round(dlpack_direct)
        if abs(observed_dev[-1] - observed_dev[-2]) > 1e-6 * abs(
            observed_dev[-2]
        ):
            raise RuntimeError(
                "device-arm push equivalence failed: dlpack=%r "
                "host-staged=%r" % (observed_dev[-1], observed_dev[-2])
            )
        results["dev_host_staged"] = timed(
            lambda: dev_round(host_staged)
        )
        results["dev_dlpack"] = timed(lambda: dev_round(dlpack_direct))
        if dev_chan.state != "on":
            raise RuntimeError(
                "device arm fell off the shm transport (state=%s) — "
                "the co-located measurement would be a bytes-path run"
                % dev_chan.state
            )
    finally:
        dev_chan.close()
        dev_client.close()
        server_dev.stop(None)
        reg_dev.close()
    results["dev_payload_mb"] = n_tensors * dev_elems * 4 / (1 << 20)
    return results


def bench_sharded(quick=False):
    """The pjit 2D dense plane (docs/distributed.md, ROADMAP item 5):
    a transformer whose REPLICATED train state exceeds the per-device
    budget trains on the ``data x model`` mesh, parameters placed by
    NamedSharding.

    Two phases:

    - EQUIVALENCE PRE-PASS (enforced, rc 1 on miss): a small
      transformer trains N steps on the replicated shard_map arm and
      on the pjit 2D-sharded arm from one common init — per-step
      losses within 1e-6 (bitwise on this toolchain) and final
      parameters within 1e-6. The sharded plane must be the SAME
      training computation, just laid out.
    - OVER-BUDGET ARM: a model sized so its replicated adam train
      state exceeds ``EDL_BENCH_DEVICE_BUDGET_MB`` per device trains
      sharded; the bench verifies the budget arithmetic both ways
      (abstract replicated footprint > budget, measured per-device
      sharded bytes < budget) and gates throughput at a floor of the
      replicated SMALL-model control (the model a budget-bound
      replicated job would be stuck with).
    """
    import jax
    import optax

    import elasticdl_tpu.parallel.distributed as dist_mod
    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    budget_mb = float(
        os.environ.get("EDL_BENCH_DEVICE_BUDGET_MB", "32")
    )
    small_kw = dict(
        vocab_size=64,
        num_layers=2,
        num_heads=4,
        head_dim=8,
        embed_dim=32,
        mlp_dim=64,
        use_flash=False,
    )
    # sized so the REPLICATED adam state (params + mu + nu) busts the
    # per-device budget while the model=4 sharding fits comfortably
    big_kw = dict(
        vocab_size=8192,
        num_layers=2,
        num_heads=8,
        head_dim=32,
        embed_dim=256,
        mlp_dim=1024,
        use_flash=False,
    )
    batch, seq = 8, 32
    steps = 4 if quick else 8
    rng = np.random.default_rng(11)

    def make_batches(kw, n):
        out = []
        for _ in range(n):
            toks = rng.integers(
                0, kw["vocab_size"], (batch, seq)
            ).astype(np.int32)
            out.append(({"tokens": toks}, toks.copy()))
        return out

    def tp_builder(kw, tp):
        def builder(mesh):
            return (
                zoo.custom_model(**kw),
                zoo.param_shardings(mesh, tensor_parallel=tp),
            )

        return builder

    def gather(tree):
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), tree
        )

    spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )
    orig_ensure = dist_mod.ensure_world
    dist_mod.ensure_world = lambda s, **k: None
    results = {}
    try:
        # -- phase 1: equivalence pre-pass --------------------------------
        pre_batches = make_batches(small_kw, 4)
        trep = ElasticDPTrainer(
            zoo.custom_model(**small_kw), zoo.loss, optax.adam(1e-3)
        )
        trep.establish(spec, example_batch=pre_batches[0])
        tsh = ElasticDPTrainer(
            zoo.custom_model(**small_kw),
            zoo.loss,
            optax.adam(1e-3),
            distributed_builder=tp_builder(small_kw, 2),
            mesh_axes_fn=lambda n: zoo.mesh_axes(n, tensor_parallel=2),
        )
        tsh.establish(spec, example_batch=pre_batches[0])
        try:
            for features, labels in pre_batches:
                l_rep, _, _ = trep.train_step(
                    features, labels, batch, sync=True
                )
                l_pjit, _, _ = tsh.train_step(
                    features, labels, batch, sync=True
                )
                if abs(l_rep - l_pjit) > 1e-6 * max(1.0, abs(l_rep)):
                    results["error"] = (
                        "pjit/replicated loss divergence: %.9f vs "
                        "%.9f" % (l_pjit, l_rep)
                    )
                    return results
            for (pa, a), (_pb, b) in zip(
                jax.tree_util.tree_leaves_with_path(
                    gather(trep._ts.params)
                ),
                jax.tree_util.tree_leaves_with_path(
                    gather(tsh._ts.params)
                ),
            ):
                if not np.allclose(a, b, rtol=1e-6, atol=1e-6):
                    results["error"] = (
                        "pjit/replicated parameter divergence at %s"
                        % (pa,)
                    )
                    return results
            # the small replicated arm doubles as the throughput
            # control: time its steady steps
            t0 = time.perf_counter()
            for features, labels in pre_batches * (steps // 2):
                trep.train_step(features, labels, batch, sync=True)
            control_eps = (
                batch * 4 * (steps // 2)
            ) / (time.perf_counter() - t0)
        finally:
            trep.close()
            tsh.close()

        # -- phase 2: the over-budget model, sharded ----------------------
        big_batches = make_batches(big_kw, 2)
        big = ElasticDPTrainer(
            zoo.custom_model(**big_kw),
            zoo.loss,
            optax.adam(1e-3),
            distributed_builder=tp_builder(big_kw, 4),
            mesh_axes_fn=lambda n: zoo.mesh_axes(n, tensor_parallel=4),
        )
        try:
            # replicated footprint from the abstract state — no
            # materialization of the big model anywhere replicated
            abstract = big._abstract_ts(big_batches[0])
            replicated_mb = sum(
                int(np.prod(l.shape, dtype=np.int64))
                * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(abstract)
            ) / (1 << 20)
            if replicated_mb <= budget_mb:
                results["error"] = (
                    "bench misconfigured: replicated footprint "
                    "%.1f MiB does not exceed the %.0f MiB budget"
                    % (replicated_mb, budget_mb)
                )
                return results
            big.establish(spec, example_batch=big_batches[0])
            # first mesh device (no jax.devices() probe — R1): the
            # established mesh already enumerates the world
            dev0 = big.mesh.devices.reshape(-1)[0]
            sharded_mb = sum(
                s.data.nbytes
                for l in jax.tree_util.tree_leaves(big._ts)
                if hasattr(l, "addressable_shards")
                for s in l.addressable_shards
                if s.device == dev0
            ) / (1 << 20)
            if sharded_mb >= budget_mb:
                results["error"] = (
                    "sharded per-device footprint %.1f MiB still "
                    "exceeds the %.0f MiB budget" % (sharded_mb, budget_mb)
                )
                return results
            big.train_step(*big_batches[0], batch, sync=True)  # compile
            t0 = time.perf_counter()
            for i in range(steps):
                loss, _, _ = big.train_step(
                    *big_batches[i % 2], batch, sync=True
                )
            sharded_eps = batch * steps / (time.perf_counter() - t0)
            if not np.isfinite(loss):
                results["error"] = "non-finite loss on the sharded arm"
                return results
        finally:
            big.close()
        results.update(
            control_eps=control_eps,
            sharded_eps=sharded_eps,
            replicated_mb=replicated_mb,
            sharded_mb=sharded_mb,
            budget_mb=budget_mb,
            ratio=sharded_eps / max(control_eps, 1e-9),
        )
        return results
    finally:
        dist_mod.ensure_world = orig_ensure


def bench_input(quick=False):
    """Serial vs pipelined worker input plane under injected latency.

    Both arms run the REAL task data service + Dataset shim end to end:
    a fake master whose ``get_task`` pays an injected RTT (the
    cross-pod dispatch latency a loopback bench hides), a reader whose
    every record pays an injected read latency, a CPU parse fn, batch
    assembly, host prefetch. The serial arm is the pre-pipeline shape —
    no task prefetch, serial map, per-element ``_tree_stack`` batching,
    synchronous per-task acks. The pipelined arm turns on
    ``task_prefetch``, ``map(num_parallel_calls)``, vectorized batch
    assembly, and the boundary-drained ack queue
    (docs/input_pipeline.md). An equivalence pass first pins that both
    arms yield IDENTICAL batch contents in IDENTICAL order for a fixed
    seed.
    """
    import threading

    from elasticdl_tpu.data.data_reader import AbstractDataReader, Metadata
    from elasticdl_tpu.data.input_stats import InputPlaneStats
    from elasticdl_tpu.master.servicer import TaskResponse
    from elasticdl_tpu.common.constants import TaskType
    from elasticdl_tpu.worker.task_data_service import TaskDataService

    # quick still needs enough work for the overlap to beat the thread
    # overhead on small hosts — undersized arms would report the
    # pipelined plane as a regression that the full run disproves
    n_tasks = 8 if quick else 12
    records_per_task = 48 if quick else 64
    rtt_s = 0.020  # injected get_task RTT
    read_lat_s = 0.0003  # injected per-record cold-read latency
    ack_lat_s = 0.010  # report_task_result shares the master RTT
    record_dim = 256
    batch_size = 16

    class _Stub:
        """Fake master: fixed task list, injected RTT, doing-set ledger."""

        def __init__(self, sleep=True):
            self._lock = threading.Lock()
            self._todo = [
                TaskResponse(
                    shard_name="shard_%d" % i,
                    start=0,
                    end=records_per_task,
                    type=TaskType.TRAINING,
                    model_version=0,
                )
                for i in range(n_tasks)
            ]
            self._next_id = 0
            self.doing = {}
            self.reports = []
            self._sleep = sleep

        def get_task(self, task_type=None):
            if self._sleep:
                time.sleep(rtt_s)
            with self._lock:
                if not self._todo:
                    return TaskResponse()  # empty shard: stream ends
                task = self._todo.pop(0)
                self._next_id += 1
                task.task_id = self._next_id
                self.doing[self._next_id] = task
                return task

        def report_task_result(self, task_id, err_msg="", exec_counters=None):
            if self._sleep:
                time.sleep(ack_lat_s)
            with self._lock:
                self.doing.pop(task_id, None)
                self.reports.append((task_id, err_msg))

    class _Reader(AbstractDataReader):
        """Deterministic synthetic records with injected read latency."""

        def __init__(self, sleep=True):
            self._sleep = sleep

        def read_records(self, task):
            shard = int(task.shard_name.split("_")[1])
            for i in range(task.start, task.end):
                if self._sleep:
                    time.sleep(read_lat_s)
                yield (
                    np.int64(shard * records_per_task + i)
                    .tobytes()
                    .ljust(8, b"\0")
                )

        def create_shards(self):
            return {}

        @property
        def metadata(self):
            return Metadata()

    def parse(record):
        # a deliberately CPU-shaped decode: seed -> deterministic batch row
        seed = int(np.frombuffer(record[:8], np.int64)[0])
        rng = np.random.default_rng(seed)
        x = rng.standard_normal(record_dim).astype(np.float32)
        x = np.tanh(x) * np.float32(seed % 7 + 1)
        return {"x": x, "y": np.int64(seed)}

    def run_arm(pipelined, sleep=True, stats=None):
        stub = _Stub(sleep=sleep)
        tds = TaskDataService(
            stub,
            False,
            data_reader=_Reader(sleep=sleep),
            task_prefetch=2 if pipelined else 0,
            ack_queue_size=8 if pipelined else 0,
            # warm whole tasks: read-ahead of task N+1 overlaps the
            # consumption of task N (memory-bounded by task_prefetch)
            prefetch_warm_records=records_per_task,
            stats=stats,
        )
        batches = []
        t0 = time.perf_counter()
        while True:
            ds = tds.get_dataset()
            if ds is None:
                break
            ds = ds.map(
                parse, num_parallel_calls=4 if pipelined else None
            ).batch(batch_size, vectorized=pipelined).prefetch(2)
            for b in ds:
                batches.append(b)
                # the worker's per-batch completion accounting: this is
                # what triggers (sync or queued) task acks
                tds.report_record_done(int(b["y"].shape[0]))
            tds.drain_acks()
        wall = time.perf_counter() - t0
        assert not stub.doing, "doing-set leak: %r" % stub.doing
        return batches, wall, stub

    # equivalence pass (no injected latency: it is a correctness check)
    serial_b, _, _ = run_arm(pipelined=False, sleep=False)
    pipe_b, _, _ = run_arm(pipelined=True, sleep=False)
    assert len(serial_b) == len(pipe_b), (len(serial_b), len(pipe_b))
    for sb, pb in zip(serial_b, pipe_b):
        np.testing.assert_array_equal(sb["x"], pb["x"])
        np.testing.assert_array_equal(sb["y"], pb["y"])

    n_examples = n_tasks * records_per_task

    def timed_arm(pipelined):
        stats = InputPlaneStats()
        batches, wall, _ = run_arm(pipelined=pipelined, stats=stats)
        got = sum(int(b["y"].shape[0]) for b in batches)
        assert got == n_examples, (got, n_examples)
        return n_examples / wall, stats.snapshot()

    serial_eps, serial_stats = timed_arm(False)
    pipe_eps, pipe_stats = timed_arm(True)
    for tag, s in (("serial", serial_stats), ("pipelined", pipe_stats)):
        print(
            "[input/%s] starved=%.0fms read=%.0fms parse=%.0fms "
            "batch=%.0fms consumer_starved=%.0fms ack=%.0fms"
            % (
                tag,
                s["task_starved_s"] * 1e3,
                s["read_s"] * 1e3,
                s["parse_s"] * 1e3,
                s["batch_s"] * 1e3,
                s["consumer_starved_s"] * 1e3,
                s["ack_s"] * 1e3,
            ),
            file=sys.stderr,
        )
    return {
        "serial": serial_eps,
        "pipelined": pipe_eps,
        "rtt_ms": rtt_s * 1e3,
        "read_lat_us": read_lat_s * 1e6,
    }


def bench_telemetry(quick=False):
    """Telemetry plane: hot-loop overhead A/B + live-endpoint check.

    Arm 1 measures the cost of the fully-engaged telemetry plane on the
    input-plane workload (the ``--input`` harness shape: real
    TaskDataService under injected get_task RTT and per-record read
    latency): per-batch rate accounting, rate-limited snapshot shipping
    into a JobTelemetry aggregator, instrumented stub methods — vs the
    IDENTICAL harness with EDL metrics disabled (the runtime toggle,
    profiling.set_metrics_enabled). The acceptance gate is overhead
    < 2%, measured as median extra process-CPU over the off arm's
    median wall (the workload is sleep-dominated, so wall-clock A/Bs
    on a small box measure scheduler jitter, not the plane).

    Arm 2 runs a REAL local job — in-process master serving over real
    gRPC, a Worker driving MasterClient, telemetry HTTP endpoint on an
    ephemeral port — and scrapes /metrics MID-JOB until the required
    families appear: per-worker examples/sec, client- and server-side
    RPC latency histograms, live task-queue depth
    (docs/observability.md).
    """
    import tempfile
    import threading
    import urllib.request

    from elasticdl_tpu.data.data_reader import AbstractDataReader, Metadata
    from elasticdl_tpu.master.servicer import TaskResponse
    from elasticdl_tpu.master.telemetry import JobTelemetry
    from elasticdl_tpu.common.constants import TaskType
    from elasticdl_tpu.utils import profiling
    from elasticdl_tpu.worker.task_data_service import TaskDataService
    from elasticdl_tpu.worker.telemetry import WorkerTelemetry

    n_tasks = 6 if quick else 10
    records_per_task = 48 if quick else 64
    rtt_s = 0.020
    read_lat_s = 0.0003
    ack_lat_s = 0.010
    record_dim = 128
    batch_size = 16

    class _Stub:
        def __init__(self, telemetry=None):
            self._lock = threading.Lock()
            self._todo = [
                TaskResponse(
                    shard_name="shard_%d" % i,
                    start=0,
                    end=records_per_task,
                    type=TaskType.TRAINING,
                    model_version=0,
                )
                for i in range(n_tasks)
            ]
            self._next_id = 0
            self.doing = {}
            self._telemetry = telemetry
            # the real servicer wrap: server-side service-time
            # histograms are part of the measured plane
            wrapped = profiling.instrument_service_methods(
                {
                    "get_task": self._get_task,
                    "report_task_result": self._report,
                },
                role="bench",
            )
            self._wrapped_get, self._wrapped_report = (
                wrapped["get_task"],
                wrapped["report_task_result"],
            )

        def _get_task(self, task_type=None):
            time.sleep(rtt_s)
            with self._lock:
                if not self._todo:
                    return TaskResponse()
                task = self._todo.pop(0)
                self._next_id += 1
                task.task_id = self._next_id
                self.doing[self._next_id] = task
                return task

        def _report(self, task_id, err_msg="", exec_counters=None):
            time.sleep(ack_lat_s)
            with self._lock:
                self.doing.pop(task_id, None)

        def get_task(self, task_type=None):
            return self._wrapped_get(task_type)

        def report_task_result(self, task_id, err_msg="", exec_counters=None):
            return self._wrapped_report(task_id, err_msg, exec_counters)

        def report_telemetry(self, snap):
            if self._telemetry is not None:
                self._telemetry.ingest(snap)

    class _Reader(AbstractDataReader):
        def read_records(self, task):
            shard = int(task.shard_name.split("_")[1])
            for i in range(task.start, task.end):
                time.sleep(read_lat_s)
                yield (
                    np.int64(shard * records_per_task + i)
                    .tobytes()
                    .ljust(8, b"\0")
                )

        def create_shards(self):
            return {}

        @property
        def metadata(self):
            return Metadata()

    def parse(record):
        seed = int(np.frombuffer(record[:8], np.int64)[0])
        rng = np.random.default_rng(seed)
        x = rng.standard_normal(record_dim).astype(np.float32)
        return {"x": np.tanh(x), "y": np.int64(seed)}

    def run_arm(metrics_on):
        profiling.set_metrics_enabled(metrics_on)
        try:
            aggregator = JobTelemetry()
            stub = _Stub(telemetry=aggregator)
            tds = TaskDataService(
                stub,
                False,
                data_reader=_Reader(),
                task_prefetch=2,
                ack_queue_size=8,
                prefetch_warm_records=records_per_task,
            )
            wt = WorkerTelemetry(0, stats=tds.stats, interval_s=0.25)
            n = 0
            t0 = time.perf_counter()
            c0 = time.process_time()
            while True:
                ds = tds.get_dataset()
                if ds is None:
                    break
                ds = (
                    ds.map(parse, num_parallel_calls=4)
                    .batch(batch_size, vectorized=True)
                    .prefetch(2)
                )
                for b in ds:
                    count = int(b["y"].shape[0])
                    n += count
                    wt.on_batch(count)
                    tds.report_record_done(count)
                    wt.ship(stub)
                tds.drain_acks()
            wt.ship(stub, force=True)
            wall = time.perf_counter() - t0
            cpu = time.process_time() - c0
            assert n == n_tasks * records_per_task, (n,)
            return n / wall, cpu, wall, aggregator
        finally:
            profiling.set_metrics_enabled(True)

    # warmup (page/thread caches), then alternate the arms; the off arm
    # runs the IDENTICAL code path with the runtime toggle off. The
    # workload is sleep-dominated by design (injected RTT + read
    # latency), so single-shot WALL times on a 2-core box swing +-15% —
    # far more than the 2% gate. The hot-loop overhead is CPU work, and
    # process CPU time doesn't tick during sleeps, so the gate compares
    # median CPU per arm, expressed as a fraction of the off arm's wall
    # (the throughput cost if every extra cycle serialized — an upper
    # bound on the examples/sec cost). Examples/sec medians ride along
    # for context.
    run_arm(True)
    reps_on, reps_off = [], []
    aggregator = None
    for rep in range(3 if quick else 5):
        eps, cpu, wall, agg = run_arm(True)
        reps_on.append((eps, cpu, wall))
        aggregator = aggregator or agg
        reps_off.append(run_arm(False)[:3])
        print(
            "telemetry A/B rep %d: on=%.1f ex/s %.3fs cpu, "
            "off=%.1f ex/s %.3fs cpu"
            % (rep + 1, eps, cpu, reps_off[-1][0], reps_off[-1][1]),
            file=sys.stderr,
        )

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    eps_on = med([r[0] for r in reps_on])
    eps_off = med([r[0] for r in reps_off])
    cpu_on = med([r[1] for r in reps_on])
    cpu_off = med([r[1] for r in reps_off])
    wall_off = med([r[2] for r in reps_off])
    overhead_pct = max(0.0, cpu_on - cpu_off) / wall_off * 100.0
    # the engaged arm must have actually aggregated something
    snaps = aggregator.worker_snapshots()
    assert snaps and snaps["0"]["examples_total"] > 0, snaps

    # -- arm 2: live local job over real gRPC + /metrics scrape -------------
    from tests.test_utils import DatasetName, create_recordio_file

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.master.rpc_service import MasterClient
    from elasticdl_tpu.worker.worker import Worker

    here = os.path.dirname(os.path.abspath(__file__))
    data_dir = tempfile.mkdtemp(prefix="edl_bench_telemetry_")
    create_recordio_file(
        96, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=data_dir
    )
    model_def = "mnist_subclass.mnist_subclass.CustomModel"
    args = parse_master_args(
        [
            "--job_name", "bench-telemetry",
            "--model_zoo", os.path.join(here, "model_zoo"),
            "--model_def", model_def,
            "--minibatch_size", "16",
            "--training_data", data_dir,
            "--num_workers", "0",
            "--num_ps_pods", "0",
            "--use_async", "true",
            "--port", "0",
            "--telemetry_port", "0",
            "--telemetry_report_secs", "0.2",
        ]
    )
    args.num_ps_pods = 0
    master = Master(args)
    master.prepare()
    stub = MasterClient("localhost:%d" % master.port)
    worker = Worker(
        0,
        master.job_type,
        16,
        os.path.join(here, "model_zoo"),
        model_def,
        stub=stub,
        telemetry_report_secs=0.2,
    )
    worker_err = []

    def _drive():
        try:
            worker.run()
        except Exception as e:  # surfaces in the verdict below
            worker_err.append(e)

    t = threading.Thread(target=_drive, name="edl-bench-worker")
    t.start()
    required = [
        'edl_worker_examples_per_sec{worker="0"}',
        "edl_rpc_client_latency_seconds_bucket",
        'edl_rpc_server_latency_seconds_bucket{role="master"',
        "edl_task_queue_depth",
    ]
    missing = list(required)
    deadline = time.monotonic() + (300 if not quick else 180)
    url = "http://127.0.0.1:%d/metrics" % master.telemetry_port
    text = ""
    while time.monotonic() < deadline:
        # scrape MID-JOB: the acceptance criterion is a live endpoint,
        # not a post-mortem dump
        text = urllib.request.urlopen(url, timeout=10).read().decode(
            "utf-8"
        )
        missing = [m for m in required if m not in text]
        if not missing or (not t.is_alive() and worker_err):
            break
        time.sleep(0.2)
    t.join(timeout=120)
    master.request_stop()
    master.run(poll_secs=0.1)
    stub.close()
    if worker_err:
        raise RuntimeError("live-job worker failed: %r" % worker_err[0])
    if missing:
        raise RuntimeError(
            "telemetry endpoint missing families: %s" % missing
        )
    return {
        "overhead_pct": overhead_pct,
        "eps_on": eps_on,
        "eps_off": eps_off,
        "endpoint_families": len(required),
    }


def bench_trace(quick=False):
    """Tracing plane (docs/observability.md "Distributed tracing"):
    overhead A/B + live-job critical path + flight-recorder kill drill.

    Arm 1 gates the FULLY-ENGAGED tracing plane (per-batch step spans
    with the worker's child-phase structure, the task data service's
    task/wait + warm + ack spans, span-context injection on every
    instrumented stub call, pending-buffer shipping) at <2% overhead
    vs the identical harness under EDL_METRICS-off — same CPU-median
    basis as the --telemetry gate (the workload is sleep-dominated,
    wall A/Bs measure scheduler jitter).

    Arm 2 runs a REAL local job (in-process master over real gRPC, a
    Worker thread), exports the master's /trace endpoint, and
    round-trips it through tools/tracetool.py: the per-step
    critical-path breakdown must attribute >=90% of traced-step wall
    time to named child spans.

    Arm 3 is the flight-recorder drill: a REAL PS shard process is
    SIGKILLed mid-conversation; the surviving client's terminal RPC
    failure emits ps_shard_failure, and the armed recorder must leave
    a postmortem JSONL whose every line parses, containing both the
    trigger event and recent spans.
    """
    import tempfile
    import threading
    import urllib.request

    from elasticdl_tpu.data.data_reader import AbstractDataReader, Metadata
    from elasticdl_tpu.master.servicer import TaskResponse
    from elasticdl_tpu.common.constants import TaskType
    from elasticdl_tpu.tools.tracetool import critical_path
    from elasticdl_tpu.utils import profiling
    from elasticdl_tpu.worker.task_data_service import TaskDataService
    from elasticdl_tpu.worker.telemetry import WorkerTelemetry

    n_tasks = 6 if quick else 10
    records_per_task = 48 if quick else 64
    rtt_s = 0.020
    read_lat_s = 0.0003
    ack_lat_s = 0.010
    batch_size = 16

    class _Stub:
        def __init__(self):
            self._lock = threading.Lock()
            self._todo = [
                TaskResponse(
                    shard_name="shard_%d" % i,
                    start=0,
                    end=records_per_task,
                    type=TaskType.TRAINING,
                    model_version=0,
                    extended_config={"trace_id": "t%06d" % (i + 1)},
                )
                for i in range(n_tasks)
            ]
            self._next_id = 0
            self.doing = {}
            wrapped = profiling.instrument_service_methods(
                {
                    "get_task": self._get_task,
                    "report_task_result": self._report,
                },
                role="bench",
            )
            self._wrapped_get, self._wrapped_report = (
                wrapped["get_task"],
                wrapped["report_task_result"],
            )

        def _get_task(self, task_type=None):
            time.sleep(rtt_s)
            with self._lock:
                if not self._todo:
                    return TaskResponse()
                task = self._todo.pop(0)
                self._next_id += 1
                task.task_id = self._next_id
                self.doing[self._next_id] = task
                return task

        def _report(self, task_id, err_msg="", exec_counters=None):
            time.sleep(ack_lat_s)
            with self._lock:
                self.doing.pop(task_id, None)

        def get_task(self, task_type=None):
            return self._wrapped_get(task_type)

        def report_task_result(self, task_id, err_msg="", exec_counters=None):
            return self._wrapped_report(task_id, err_msg, exec_counters)

        def report_telemetry(self, snap):
            pass

    class _Reader(AbstractDataReader):
        def read_records(self, task):
            shard = int(task.shard_name.split("_")[1])
            for i in range(task.start, task.end):
                time.sleep(read_lat_s)
                yield (
                    np.int64(shard * records_per_task + i)
                    .tobytes()
                    .ljust(8, b"\0")
                )

        def create_shards(self):
            return {}

        @property
        def metadata(self):
            return Metadata()

    def parse(record):
        return {"x": np.frombuffer(record[:8], np.int64).copy()}

    def run_arm(metrics_on):
        profiling.set_metrics_enabled(metrics_on)
        try:
            stub = _Stub()
            tds = TaskDataService(
                stub,
                False,
                data_reader=_Reader(),
                task_prefetch=2,
                ack_queue_size=8,
                prefetch_warm_records=records_per_task,
            )
            wt = WorkerTelemetry(0, stats=tds.stats, interval_s=0.25)
            n = 0
            t0 = time.perf_counter()
            c0 = time.process_time()
            while True:
                ds = tds.get_dataset()
                if ds is None:
                    break
                ds = (
                    ds.map(parse, num_parallel_calls=4)
                    .batch(batch_size, vectorized=True)
                    .prefetch(2)
                )
                for b in ds:
                    count = int(b["x"].shape[0])
                    n += count
                    task = tds.get_current_task()
                    trace = (
                        (task.extended_config or {}).get("trace_id")
                        if task is not None
                        else None
                    )
                    # the worker step-span structure, fully engaged:
                    # root + the child phases the breakdown decomposes
                    with profiling.span(
                        "step", trace_id=trace, examples=count
                    ):
                        with profiling.span("step/compute"):
                            float(np.tanh(b["x"]).sum())
                        with profiling.span("step/grad_push"):
                            pass
                    wt.on_batch(count)
                    tds.report_record_done(count)
                    wt.ship(stub)
                tds.drain_acks()
            wt.ship(stub, force=True)
            wall = time.perf_counter() - t0
            cpu = time.process_time() - c0
            assert n == n_tasks * records_per_task, (n,)
            return n / wall, cpu, wall
        finally:
            profiling.set_metrics_enabled(True)

    run_arm(True)  # warmup
    reps_on, reps_off = [], []
    for rep in range(3 if quick else 5):
        reps_on.append(run_arm(True))
        reps_off.append(run_arm(False))
        print(
            "trace A/B rep %d: on=%.1f ex/s %.3fs cpu, "
            "off=%.1f ex/s %.3fs cpu"
            % (
                rep + 1,
                reps_on[-1][0],
                reps_on[-1][1],
                reps_off[-1][0],
                reps_off[-1][1],
            ),
            file=sys.stderr,
        )

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    eps_on = med([r[0] for r in reps_on])
    eps_off = med([r[0] for r in reps_off])
    cpu_on = med([r[1] for r in reps_on])
    cpu_off = med([r[1] for r in reps_off])
    wall_off = med([r[2] for r in reps_off])
    overhead_pct = max(0.0, cpu_on - cpu_off) / wall_off * 100.0

    # -- arm 2: live job over real gRPC -> /trace -> tracetool --------------
    from tests.test_utils import DatasetName, create_recordio_file

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.master.rpc_service import MasterClient
    from elasticdl_tpu.worker.worker import Worker

    # arm 1 filled the span ring with synthetic sleep-dominated steps;
    # the live job's breakdown must read ONLY its own spans
    profiling.spans.reset()
    here = os.path.dirname(os.path.abspath(__file__))
    data_dir = tempfile.mkdtemp(prefix="edl_bench_trace_")
    n_records = 96 if quick else 160
    create_recordio_file(
        n_records, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=data_dir
    )
    model_def = "mnist_subclass.mnist_subclass.CustomModel"
    args = parse_master_args(
        [
            "--job_name", "bench-trace",
            "--model_zoo", os.path.join(here, "model_zoo"),
            "--model_def", model_def,
            "--minibatch_size", "16",
            "--training_data", data_dir,
            "--num_workers", "0",
            "--num_ps_pods", "0",
            "--use_async", "true",
            "--port", "0",
            "--telemetry_port", "0",
            "--telemetry_report_secs", "0.2",
        ]
    )
    args.num_ps_pods = 0
    master = Master(args)
    master.prepare()
    stub = MasterClient("localhost:%d" % master.port)
    worker = Worker(
        0,
        master.job_type,
        16,
        os.path.join(here, "model_zoo"),
        model_def,
        stub=stub,
        telemetry_report_secs=0.2,
    )
    worker_err = []

    def _drive():
        try:
            worker.run()
        except Exception as e:
            worker_err.append(e)

    t = threading.Thread(target=_drive, name="edl-bench-trace-worker")
    t.start()
    t.join(timeout=300 if not quick else 180)
    trace_doc = json.loads(
        urllib.request.urlopen(
            "http://127.0.0.1:%d/trace" % master.telemetry_port,
            timeout=10,
        ).read()
    )
    master.request_stop()
    master.run(poll_secs=0.1)
    stub.close()
    if worker_err:
        raise RuntimeError("live-job worker failed: %r" % worker_err[0])
    report = critical_path(trace_doc)
    if not report["steps"]:
        raise RuntimeError(
            "live job produced no step spans on /trace "
            "(%d trace events)" % len(trace_doc.get("traceEvents", []))
        )
    print(
        "trace live job: %d steps, attribution %.1f%%, phases %s"
        % (
            report["steps"],
            100.0 * report["attribution"],
            {
                k: v["share"]
                for k, v in report["phases"].items()
            },
        ),
        file=sys.stderr,
    )

    # -- arm 3: flight-recorder drill (real SIGKILL of a live PS) -----------
    from elasticdl_tpu.worker.ps_client import BoundPS, PSRpcError

    fr_dir = tempfile.mkdtemp(prefix="edl_bench_trace_fr_")
    err_dir = tempfile.mkdtemp(prefix="edl_bench_trace_ps_")
    profiling.flight_recorder.arm(fr_dir, min_interval_s=0.0)
    procs, addrs = _launch_ps_fleet(
        err_dir,
        os.path.join(here, "model_zoo"),
        "deepfm_edl_embedding.deepfm_edl_embedding.custom_model",
        "trace-fr",
        n=1,
    )
    postmortem = None
    try:
        bound = BoundPS(addrs[0], deadline_s=5.0, retries=0)
        try:
            resp = bound.pull_variable({})
            assert "model_init_status" in resp, resp
            procs[0][0].kill()  # SIGKILL: no drain, no goodbye
            procs[0][0].wait(timeout=10)
            try:
                with profiling.span("step", trace_id="chaos-drill"):
                    bound.pull_variable({})
                raise RuntimeError(
                    "pull against the killed shard unexpectedly "
                    "succeeded"
                )
            except PSRpcError:
                pass  # the expected terminal failure
        finally:
            bound.close()
    finally:
        _stop_ps_fleet(procs)
        profiling.flight_recorder.disarm()
    dumps = sorted(
        f
        for f in os.listdir(fr_dir)
        if f.startswith("postmortem-")
    )
    if not dumps:
        raise RuntimeError(
            "PS SIGKILL left no flight-recorder postmortem in %s"
            % fr_dir
        )
    postmortem = os.path.join(fr_dir, dumps[-1])
    lines = [
        json.loads(l)
        for l in open(postmortem, encoding="utf-8")
        if l.strip()
    ]
    header = lines[0]
    assert header["postmortem"] == "ps_shard_failure", header
    kinds = {
        e.get("kind") for e in lines[1:] if e.get("type") == "event"
    }
    assert "ps_shard_failure" in kinds, kinds
    assert any(e.get("type") == "span" for e in lines[1:]), (
        "postmortem carries no spans"
    )
    print(
        "flight recorder: %s (%d lines, all parseable)"
        % (postmortem, len(lines)),
        file=sys.stderr,
    )
    return {
        "overhead_pct": overhead_pct,
        "eps_on": eps_on,
        "eps_off": eps_off,
        "steps": report["steps"],
        "attribution": report["attribution"],
        "postmortem_lines": len(lines),
    }


def bench_resnet(quick=False, profile_dir=None):
    """Fused jitted ResNet-50 train step (fwd+bwd+SGD, bf16 MXU compute)
    with on-device synthetic data: the compute-path ceiling the input
    pipeline must keep fed. Returns examples/sec/chip."""
    import jax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.imagenet_resnet50 import imagenet_resnet50 as zoo

    # CPU backends get the quick-sized workload: the production b128
    # im224 step runs minutes-per-step on CPU and wedged the whole
    # suite (BENCH_r05 rc=124); main() publishes the shrunk number
    # under a _cpu metric suffix so the accelerator ratchet stays clean
    shrink = quick or _on_cpu()
    batch = 32 if shrink else 128
    image = 64 if shrink else 224
    steps = 3 if shrink else 20

    model = zoo.custom_model()
    rng = np.random.default_rng(0)
    features = {
        "image": rng.random((batch, image, image, 3), dtype=np.float32)
    }
    labels = rng.integers(0, 1000, size=(batch, 1)).astype(np.int32)

    variables = init_variables(
        model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
    )
    params, state = split_variables(variables)
    optimizer = zoo.optimizer()
    ts = TrainState.create(params, state, optimizer)
    step_fn = make_train_step(model, zoo.loss, optimizer)

    dev_features = jax.device_put(features)
    dev_labels = jax.device_put(labels)
    step_rng = jax.random.PRNGKey(1)

    # warmup/compile. Synchronize with a host scalar fetch, not
    # block_until_ready: some remote-execution transports (the axon dev
    # tunnel) return from block_until_ready before compute completes, and
    # only a device->host read forces full execution.
    for _ in range(2):
        ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
    float(loss)

    if profile_dir:
        from elasticdl_tpu.utils.profiling import trace

        ctx = trace(profile_dir)
    else:
        import contextlib

        ctx = contextlib.nullcontext()

    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            ts, loss = step_fn(ts, dev_features, dev_labels, step_rng)
        final_loss = float(loss)
        dt = time.perf_counter() - t0
    if not np.isfinite(final_loss):
        raise RuntimeError("non-finite loss in resnet benchmark")
    return batch * steps / dt


def main(argv=None):
    argv = argv or sys.argv[1:]
    quick = "--quick" in argv
    update = "--update-baseline" in argv and not quick

    if "--transformer" in argv:
        use_flash = "--no-flash" not in argv
        large = "--large" in argv
        cpu = not quick and _on_cpu()
        tokens_per_sec, mfu, desc = bench_transformer(
            quick, use_flash, large=large
        )
        metric = (
            "transformer_lm_tokens_per_sec_per_chip"
            # quick/cpu modes run the toy config regardless of --large:
            # they must not publish under (or ratchet against) the 730M
            # name
            + ("_730m" if large and not (quick or cpu) else "")
            + ("" if use_flash else "_noflash")
            # toy-config runs must not compare against the production
            # ratchet either (mirrors the --flash per-L metric naming)
            + ("_quick" if quick else "_cpu" if cpu else "")
        )
        _emit(
            metric,
            round(tokens_per_sec, 0),
            "tokens/sec/chip (%s; MFU %.3f)" % (desc, mfu),
            update,
        )
        return 0

    if "--flash" in argv:
        cpu = not quick and _on_cpu()
        if cpu:
            # Pallas runs in interpret mode off-TPU: L=2048 would take
            # the whole suite budget — measure a toy length and name
            # the metric after it so no accelerator ratchet is touched
            speedup, at_len = bench_flash(True, lengths=(256,))
        elif "--l2048" in argv:
            # the suite's single-length form: just the ratcheted L
            speedup, at_len = bench_flash(quick, lengths=(2048,))
        else:
            speedup, at_len = bench_flash(quick)
        # metric name carries the measured L: a --quick run (L=1024)
        # must not compare against the published L=2048 ratchet
        _emit(
            "flash_attention_speedup_l%d" % at_len + ("_cpu" if cpu else ""),
            round(speedup, 2),
            "x vs XLA reference attention (fwd+bwd, b4 h8 d64, causal)",
            update,
        )
        return 0

    if "--longcontext" in argv:
        best = bench_longcontext(quick)
        if best is None:
            print(json.dumps({"error": "no long-context shape completed"}))
            return 1
        max_len, tok_s = best
        _emit(
            "flash_attention_max_context_tokens_per_sec",
            round(tok_s, 0),
            "tokens/sec/layer fwd+bwd at L=%d, b1 h8 d64 (XLA unfused "
            "attention fails from L=16384 up)" % max_len,
            update,
        )
        return 0

    if "--sharded" in argv:
        # multi-device CPU mesh, pinned BEFORE any jax import below
        _force_cpu_mesh(8)
        res = bench_sharded(quick)
        if "error" in res:
            print(
                json.dumps(
                    {
                        "metric": "sharded_dense_examples_per_sec",
                        "error": "pjit dense plane gate failed: %s"
                        % res["error"],
                    }
                )
            )
            return 1
        floor = 0.02
        if res["ratio"] < floor:
            print(
                json.dumps(
                    {
                        "metric": "sharded_dense_examples_per_sec",
                        "error": "sharded throughput %.1f ex/s is "
                        "%.3fx the replicated small-model control "
                        "(%.1f ex/s) — below the %.2fx floor"
                        % (
                            res["sharded_eps"],
                            res["ratio"],
                            res["control_eps"],
                            floor,
                        ),
                    }
                )
            )
            return 1
        _emit(
            "sharded_dense_examples_per_sec",
            round(res["sharded_eps"], 1),
            "examples/sec training a transformer whose REPLICATED "
            "adam train state (%.0f MiB/device) exceeds the %.0f MiB "
            "per-device budget, on the 2D data x model pjit mesh "
            "(measured sharded footprint %.1f MiB/device; %.2fx the "
            "replicated small-model control's %.1f ex/s, floor "
            "%.2fx). Equivalence pre-pass: pjit arm matches the "
            "replicated arm's losses and parameters at 1e-6 from one "
            "common init (rc 1 on miss)"
            % (
                res["replicated_mb"],
                res["budget_mb"],
                res["sharded_mb"],
                res["ratio"],
                res["control_eps"],
                floor,
            ),
            update,
        )
        return 0

    if "--compile" in argv:
        # multi-device CPU mesh, pinned BEFORE any jax import below
        _force_cpu_mesh(8)
        res = bench_compile(quick)
        _emit(
            "compile_cached_establish_speedup",
            round(
                res["cold_revisit_s"] / max(res["cached_revisit_s"], 1e-9),
                2,
            ),
            "x resize pause at a previously-seen world size, executable "
            "cache vs cold recompile (cold %.2fs, cached %.2fs; pause = "
            "snapshot + mesh re-form + state re-broadcast + step "
            "acquisition + first step; equivalence pre-pass: "
            "bit-identical train state)"
            % (res["cold_revisit_s"], res["cached_revisit_s"]),
            update,
        )
        _emit(
            "compile_speculative_resize_speedup",
            round(res["cached_worst_s"] / max(res["spec_worst_s"], 1e-9), 2),
            "x worst resize pause, speculative background AOT vs "
            "cache-only (cache-only worst %.2fs — its first visit to a "
            "new size compiles cold; speculative worst %.2fs — the "
            "hinted size was compiled during steady-state training)"
            % (res["cached_worst_s"], res["spec_worst_s"]),
            update,
        )
        _emit(
            "compile_overlap_step_speedup",
            round(res["overlap_eps"] / max(res["sync_eps"], 1e-9), 2),
            "x hot-loop examples/s, deferred-sync dispatch + "
            "collect-later loss drains + feeder-thread H2D staging vs "
            "per-step blocking sync (%.0f vs %.0f ex/s; both arms "
            "record every step's loss, streams bitwise equal; on the "
            "CPU bench mesh the per-step round trip costs ~nothing, so "
            "~1x here — the machinery exists for the ~10ms/step "
            "tunneled-TPU fetch RTT the sync arm pays per step)"
            % (res["overlap_eps"], res["sync_eps"]),
            update,
        )
        return 0

    if "--resize" in argv:
        # multi-device CPU mesh, pinned BEFORE any jax import below
        _force_cpu_mesh(8)
        try:
            res = bench_resize(quick)
        except RuntimeError as exc:
            # the arm's own hard gates (no layout change forced /
            # bitwise relayout mismatch) — machine-readable, rc 1
            print(
                json.dumps(
                    {
                        "metric": "resize_layout_speculative_pause_ratio",
                        "error": "layout re-solve gate failed: %s" % exc,
                    }
                )
            )
            return 1
        failures = 0
        if res["pause_ratio"] > 0.5:
            failures = 1
            print(
                json.dumps(
                    {
                        "metric": "resize_layout_speculative_pause_ratio",
                        "error": "planned resize pause %.2fs is %.2fx "
                        "the cold re-solve pause %.2fs — above the "
                        "0.5x ceiling"
                        % (
                            res["planned_pause_s"],
                            res["pause_ratio"],
                            res["cold_pause_s"],
                        ),
                    }
                )
            )
        else:
            _emit(
                "resize_layout_speculative_pause_ratio",
                round(res["pause_ratio"], 2),
                "x planned (layout-hinted speculative AOT) vs cold "
                "re-solve pause for the budget-forced %s -> %s layout "
                "change (planned %.2fs, cold %.2fs; pause = establish "
                "+ first step; ceiling 0.50x, rc 1 above; state "
                "carried bitwise through the direct relayout)"
                % (
                    "dp%dxtp%d" % res["pre_layout"][:2],
                    "dp%dxtp%d" % res["post_layout"][:2],
                    res["planned_pause_s"],
                    res["cold_pause_s"],
                ),
                update,
                lower_is_better=True,
            )
        if res["examples_ratio"] < 1.0:
            failures = 1
            print(
                json.dumps(
                    {
                        "metric": "resize_solver_vs_naive_examples_ratio",
                        "error": "solver-chosen layout trains %.1f "
                        "ex/s, %.2fx naive dp-only's %.1f ex/s — "
                        "below the 1.0x floor"
                        % (
                            res["solver_eps"],
                            res["examples_ratio"],
                            res["naive_eps"],
                        ),
                    }
                )
            )
        else:
            _emit(
                "resize_solver_vs_naive_examples_ratio",
                round(res["examples_ratio"], 2),
                "x examples/sec, solver-chosen %s mb%d vs naive "
                "dp-only at the micro-batch the budget admits "
                "(%.0f vs %.0f ex/s on the over-budget transformer; "
                "floor 1.0x, rc 1 below)"
                % (
                    "dp%dxtp%d" % res["post_layout"][:2],
                    res["post_layout"][2],
                    res["solver_eps"],
                    res["naive_eps"],
                ),
                update,
            )
        return failures

    if "--elastic-tax" in argv:
        overhead_pct, fused, elastic = bench_elastic_tax(quick)
        _emit(
            "elastic_step_overhead_pct" + ("_quick" if quick else ""),
            round(overhead_pct, 2),
            "%% step-rate cost of the elastic weighted step vs the fused "
            "step (ResNet50 b128; fused %.0f ex/s, elastic %.0f ex/s)"
            % (fused, elastic),
            update,
        )
        return 0

    if "--embedding" in argv:
        results = bench_embedding(quick)
        _emit(
            "hbm_embedding_a2a_rows_per_sec",
            round(results["a2a"], 0),
            "rows/sec fwd+bwd (%s; take %.2fM/s psum %.2fM/s)"
            % (
                results["_desc"],
                results["take"] / 1e6,
                results["psum"] / 1e6,
            ),
            update,
        )
        return 0

    if "--ps" in argv:
        res = bench_ps(quick)
        _emit(
            "ps_deepfm_examples_per_sec",
            round(res["examples_per_sec"], 1),
            "examples/sec, deepfm vs 2 OS-process PS over loopback "
            "gRPC, async push/pull per step (bf16 wire: %.1f ex/s, "
            "%.2fx)"
            % (
                res["examples_per_sec_bf16"],
                res["examples_per_sec_bf16"]
                / max(res["examples_per_sec"], 1e-9),
            ),
            update,
        )
        _emit(
            "ps_deepfm_examples_per_sec_fastpath",
            round(res["examples_per_sec_fastpath"], 1),
            "examples/sec on a >=5x-duplicated power-law id file with "
            "the sparse fast path (batch dedup + row-combined push + "
            "hot-row cache); vs %.1f ex/s with dedup, combine AND "
            "cache all disabled — the per-occurrence wire behavior "
            "(fast path %.2fx)"
            % (
                res["examples_per_sec_dup_naive"],
                res["examples_per_sec_fastpath"]
                / max(res["examples_per_sec_dup_naive"], 1e-9),
            ),
            update,
        )
        _emit(
            "ps_deepfm_examples_per_sec_overlap",
            round(res["examples_per_sec_overlap"], 1),
            "examples/sec with the overlapped data plane (concurrent "
            "shard fan-out + double-buffered async push, "
            "get_model_steps=4) vs %.1f ex/s through the serial "
            "per-shard loop with synchronous pushes (overlap %.2fx; "
            "both arms on the 2-process fleet with %.0f ms injected "
            "per-RPC RTT — the cross-pod latency a real PS deployment "
            "pays and a loopback bench otherwise hides)"
            % (
                res["examples_per_sec_serial"],
                res["examples_per_sec_overlap"]
                / max(res["examples_per_sec_serial"], 1e-9),
                res["overlap_rtt_ms"],
            ),
            update,
        )
        _emit(
            "ps_fanout_slow_shard_speedup",
            round(
                res["fanout_serial_call_s"]
                / max(res["fanout_overlap_call_s"], 1e-9),
                2,
            ),
            "x serial/fan-out per-call wall, 4 shards with one 4x-slow "
            "shard injected: fan-out wall %.0f ms tracks the slowest "
            "shard (%.0f ms), serial wall %.0f ms tracks the shard sum "
            "(%.0f ms)"
            % (
                res["fanout_overlap_call_s"] * 1e3,
                res["fanout_slowest_shard_s"] * 1e3,
                res["fanout_serial_call_s"] * 1e3,
                res["fanout_shard_sum_s"] * 1e3,
            ),
            update,
        )
        dev = bench_ps_device(quick)
        eq = dev.get("equivalence", {})
        if not eq.get("ok"):
            print(
                json.dumps(
                    {
                        "metric": "ps_device_apply_speedup",
                        "error": "host/device equivalence pre-pass "
                        "FAILED (%s): the device shard is not bitwise "
                        "the same trainer; speedups withheld"
                        % ", ".join(
                            k for k, v in eq.items() if k != "ok" and not v
                        ),
                    }
                )
            )
            return 1
        floor = 1.3
        for arm in ("dense", "sparse"):
            if dev["%s_speedup" % arm] < floor:
                print(
                    json.dumps(
                        {
                            "metric": "ps_device_apply_speedup",
                            "error": "device-apply shard %.2fx the "
                            "host-apply shard on the %s arm (%.2f vs "
                            "%.2f ms/step) — below the %.1fx gate at "
                            "production payload sizes"
                            % (
                                dev["%s_speedup" % arm],
                                arm,
                                dev["%s_host_s" % arm] * 1e3,
                                dev["%s_device_s" % arm] * 1e3,
                                floor,
                            ),
                        }
                    )
                )
                return 1
        _emit(
            "ps_device_apply_speedup",
            round(dev["dense_speedup"], 2),
            "x host-apply/device-apply per-step wall on the dense arm "
            "(%.1f MiB sgd model, %.2f vs %.2f ms push+pull; sparse "
            "arm %.2fx, %d-id zipf adam pushes %.2f vs %.2f ms), "
            "in-process shard pairs at steady state, min of %d "
            "alternating rounds, gate >=%.1fx both arms; equivalence "
            "pre-pass: bitwise-identical pulled params, embedding "
            "rows, and slot tables (docs/ps_device.md)"
            % (
                dev["dense_mib"],
                dev["dense_host_s"] * 1e3,
                dev["dense_device_s"] * 1e3,
                dev["sparse_speedup"],
                dev["sparse_batch_ids"],
                dev["sparse_host_s"] * 1e3,
                dev["sparse_device_s"] * 1e3,
                dev["rounds"],
                floor,
            ),
            update,
        )
        return 0

    if "--tiered" in argv:
        res = bench_tiered(quick)
        eq = res.get("equivalence", {})
        if not eq.get("ok"):
            print(
                json.dumps(
                    {
                        "metric": "ps_tiered_examples_per_sec",
                        "error": "all-in-memory/tiered equivalence "
                        "pre-pass FAILED (%s): the tiered store is not "
                        "bitwise the same table; throughput withheld"
                        % ", ".join(
                            k for k, v in eq.items() if k != "ok" and not v
                        ),
                    }
                )
            )
            return 1
        min_distinct = min(res["distinct_rows_per_shard"])
        if min_distinct < 4 * res["warm_rows"]:
            print(
                json.dumps(
                    {
                        "metric": "ps_tiered_examples_per_sec",
                        "error": "workload too small to prove the tier: "
                        "a shard sees only %d distinct feature rows "
                        "against its %d-row warm budget (need >= 4x)"
                        % (min_distinct, res["warm_rows"]),
                    }
                )
            )
            return 1
        counters = res.get("tiered_counters", {})
        spilled = counters.get("spilled_rows", 0)
        cold = counters.get("cold_pull_rows", 0)
        if spilled <= 0 or cold <= 0:
            print(
                json.dumps(
                    {
                        "metric": "ps_tiered_examples_per_sec",
                        "error": "disk tier not provably exercised: "
                        "spilled_rows=%d cold_pull_rows=%d (both must "
                        "be > 0 in the fleet's ps_status counters)"
                        % (spilled, cold),
                    }
                )
            )
            return 1
        floor = float(os.environ.get("EDL_BENCH_TIERED_FLOOR", "0.5"))
        eps_mem = res["examples_per_sec_memory"]
        eps_tier = res["examples_per_sec_tiered"]
        ratio = eps_tier / max(eps_mem, 1e-9)
        if ratio < floor:
            print(
                json.dumps(
                    {
                        "metric": "ps_tiered_examples_per_sec",
                        "error": "tiered fleet %.1f ex/s is %.2fx the "
                        "all-in-memory fleet (%.1f ex/s) — below the "
                        "%.2fx floor (EDL_BENCH_TIERED_FLOOR)"
                        % (eps_tier, ratio, eps_mem, floor),
                    }
                )
            )
            return 1
        _emit(
            "ps_tiered_examples_per_sec",
            round(eps_tier, 1),
            "examples/sec, deepfm vs a 2-process PS fleet whose "
            "per-table warm tier is %d rows against a %d-id zipf "
            "stream putting >= %d distinct rows on each shard — >= 4x "
            "its warm budget (%.2fx the all-in-memory fleet's %.1f "
            "ex/s, floor %.2fx; fleet counters: %d rows spilled, %d "
            "cold-pulled). Equivalence pre-pass: tiered arm matches "
            "the all-in-memory arm bitwise on lookups, applied rows "
            "and the full table from one common init, across a forced "
            "tier crossing (rc 1 on miss; docs/tiered_store.md)"
            % (
                res["warm_rows"],
                res["pool_ids"],
                min_distinct,
                ratio,
                eps_mem,
                floor,
                spilled,
                cold,
            ),
            update,
        )
        return 0

    if "--hybrid" in argv:
        res = bench_hybrid(quick)
        eq = res.get("equivalence", {})
        if not eq.get("ok"):
            print(
                json.dumps(
                    {
                        "metric": "ps_deepfm_examples_per_sec_hybrid",
                        "error": "hybrid/PS equivalence pre-pass FAILED "
                        "(%s): the hybrid plane is not numerically the "
                        "same trainer; speedup withheld"
                        % ", ".join(
                            k for k, v in eq.items() if k != "ok" and not v
                        ),
                    }
                )
            )
            return 1
        ratio = res["examples_per_sec_hybrid"] / max(
            res["examples_per_sec_ps"], 1e-9
        )
        if ratio < 1.3:
            print(
                json.dumps(
                    {
                        "metric": "ps_deepfm_examples_per_sec_hybrid",
                        "error": "hybrid plane %.2fx the PS-everything "
                        "arm (%.1f vs %.1f ex/s) — below the 1.3x gate "
                        "on the %dms injected-RTT fleet"
                        % (
                            ratio,
                            res["examples_per_sec_hybrid"],
                            res["examples_per_sec_ps"],
                            int(res["rtt_ms"]),
                        ),
                    }
                )
            )
            return 1
        _emit(
            "ps_deepfm_examples_per_sec_hybrid",
            round(res["examples_per_sec_hybrid"], 1),
            "examples/sec in hybrid comm-plane mode (dense + bias "
            "table local, PS-plane feature table served by the "
            "overlapped pull, sparse-only async pushes) vs %.1f ex/s "
            "with EVERYTHING on the PS fleet at its best config "
            "(fan-out + push window + get_model_steps=4): hybrid "
            "%.2fx (gate >=1.3x), both arms on the 2-process fleet "
            "with %.0f ms injected per-RPC RTT; equivalence pre-pass: "
            "bitwise-identical lookups, loss, dense and embedding-row "
            "gradients from a common init"
            % (
                res["examples_per_sec_ps"],
                ratio,
                res["rtt_ms"],
            ),
            update,
        )
        return 0

    if "--chaos" in argv:
        res = bench_chaos(quick)
        problems = []
        if not res.get("restored_saw_shard_restore_event"):
            problems.append(
                "no ps_shard_restore event: the worker never detected "
                "the relaunched incarnation"
            )
        if not res.get("restored_saw_shard_failure_event"):
            problems.append("no ps_shard_failure event recorded")
        if res.get("restored_restored_version", -1) < 0:
            problems.append(
                "relaunched shard did not restore a snapshot "
                "(restored_version=%r)"
                % res.get("restored_restored_version")
            )
        if res.get("reinit_restored_version", -1) >= 0:
            problems.append(
                "durability-off control arm unexpectedly restored state"
            )
        if res.get("restored_rollback_depth", -1) > res["cadence"] + 1:
            # +1: one version may land between the cadence capture and
            # the kill observation
            problems.append(
                "rollback depth %d exceeds the snapshot cadence %d"
                % (res.get("restored_rollback_depth", -1), res["cadence"])
            )
        ratio = res["divergence_ratio"]
        if not ratio < 0.5:
            problems.append(
                "restored arm diverged %.3fx the reinit arm's distance "
                "from the fault-free run (gate <0.5x: restoring the "
                "snapshot must land the fleet far closer to the "
                "fault-free params than the silent-reinit hazard does)"
                % ratio
            )
        # -- master recovery arm gates (docs/master_recovery.md) -------
        m_expected = res.get("master_expected_tasks", -1)
        m_clean = res.get("master_clean_journal") or {}
        m_chaos = res.get("master_chaos_journal") or {}
        if (
            m_clean.get("done") != m_expected
            or m_clean.get("pending")
        ):
            problems.append(
                "master fault-free arm accounting off: %r "
                "(expected %d done, 0 pending)" % (m_clean, m_expected)
            )
        if m_chaos.get("done") != m_expected:
            problems.append(
                "master chaos arm lost or double-counted tasks: "
                "journal done=%r, expected exactly %d"
                % (m_chaos.get("done"), m_expected)
            )
        if m_chaos.get("pending"):
            problems.append(
                "master chaos arm left %r task(s) pending in the "
                "journal" % m_chaos.get("pending")
            )
        if not res.get("master_chaos_worker_survived"):
            problems.append(
                "the worker did not survive the master outage"
            )
        if res.get("master_chaos_epoch_final") != res.get(
            "master_chaos_epoch_initial", 0
        ) + 1:
            problems.append(
                "master_epoch did not advance exactly once across the "
                "kill: %r -> %r"
                % (
                    res.get("master_chaos_epoch_initial"),
                    res.get("master_chaos_epoch_final"),
                )
            )
        m_ratio = res.get("master_divergence_ratio")
        if m_ratio is None or not m_ratio < 1.0:
            problems.append(
                "master chaos arm's final fleet state diverged %.3fx "
                "the fault-free noise floor (L2 between two fault-free "
                "runs under different task-shuffle seeds); gate <1.0x: "
                "a master kill+replay must perturb the model no more "
                "than an organic task reorder (measured ~0.03x)"
                % (m_ratio if m_ratio is not None else float("nan"))
            )
        if problems:
            print(
                json.dumps(
                    {
                        "metric": "ps_chaos_recovery_divergence",
                        "error": "; ".join(problems),
                        "detail": res,
                    }
                )
            )
            return 1
        _emit(
            "ps_chaos_recovery_divergence",
            round(max(ratio, 1e-4), 4),
            "x L2 divergence of final fleet state (dense params + every "
            "trained embedding row) from the fault-free run: "
            "snapshot-restored relaunch vs the durability-off "
            "silent-reinit control (lower=better; gate <0.5). SIGKILL "
            "one of 2 PS shards at version %d, %d-version snapshot "
            "cadence: restored arm rolled back %d <= cadence, restored "
            "v%d, both chaos jobs completed, ps_shard_failure->"
            "ps_shard_restore telemetry emitted (restored L2 %.4f vs "
            "reinit L2 %.4f)"
            % (
                res["kill_at_version"],
                res["cadence"],
                res["restored_rollback_depth"],
                res["restored_restored_version"],
                res["l2_restored_vs_clean"],
                res["l2_reinit_vs_clean"],
            ),
            update,
            lower_is_better=True,
        )
        _emit(
            "master_chaos_recovery_divergence",
            round(max(res["master_divergence_ratio"], 1e-4), 4),
            "x L2 divergence of the final fleet state after a "
            "SIGKILL-the-MASTER mid-job (journal replay + worker "
            "failover, docs/master_recovery.md) vs the fault-free "
            "noise floor (two fault-free runs under different "
            "task-shuffle seeds; lower=better, gate <1.0). Kill at %d "
            "of %d done tasks: journal counted every task done "
            "exactly once (%d dispatched, %d requeued at recovery, "
            "%d replayed ack(s) deduped, 0 pending), the in-process "
            "worker rode the outage out on the failover channel, and "
            "master_epoch advanced %d->%d across the relaunch"
            % (
                res.get("master_kill_at_done", -1),
                res["master_expected_tasks"],
                res["master_chaos_journal"].get("dispatched", -1),
                res["master_chaos_journal"].get("requeued", -1),
                res["master_chaos_journal"].get("deduped", -1),
                res.get("master_chaos_epoch_initial", -1),
                res.get("master_chaos_epoch_final", -1),
            ),
            update,
            lower_is_better=True,
        )
        return 0

    if "--serve" in argv:
        res = bench_serve(quick)
        problems = []
        try:
            p99_gate_ms = float(
                os.environ.get("EDL_BENCH_SERVE_P99_MS", "2000")
            )
        except ValueError:
            p99_gate_ms = 2000.0
        window = res["staleness_window"]
        if res.get("requests_ok", 0) <= 0:
            problems.append("no score request succeeded")
        if not (0 < res.get("p99_ms", -1.0) < p99_gate_ms):
            problems.append(
                "p99 latency %.0f ms outside the <%.0f ms gate "
                "(p50 %.0f ms)"
                % (
                    res.get("p99_ms", -1.0),
                    p99_gate_ms,
                    res.get("p50_ms", -1.0),
                )
            )
        for i, lag in enumerate(res.get("staleness", [])):
            if not 0 <= lag <= window:
                problems.append(
                    "scorer %d staleness gauge %.1f outside "
                    "[0, %d] after the PS shard kill+restore "
                    "(missing gauge = -1)" % (i, lag, window)
                )
        for i, (first, final) in enumerate(
            zip(res.get("first_versions", []), res.get("final_versions", []))
        ):
            if final <= first:
                problems.append(
                    "scorer %d never hot-swapped under live churn "
                    "(model_version %d -> %d)" % (i, first, final)
                )
        if res.get("failures_outside_outage", 0):
            problems.append(
                "%d request(s) failed OUTSIDE the shard-kill outage "
                "window" % res["failures_outside_outage"]
            )
        if res.get("post_recovery_scores_ok", 0) < res["n_scorers"]:
            problems.append(
                "only %d/%d scorers answered after the shard "
                "relaunch"
                % (
                    res.get("post_recovery_scores_ok", 0),
                    res["n_scorers"],
                )
            )
        # -- micro-batching gates (PR-18, docs/serving.md) ----------
        def _env_float(name, default):
            try:
                return float(os.environ.get(name, str(default)))
            except ValueError:
                return default

        speedup_gate = _env_float("EDL_BENCH_SERVE_BATCH_SPEEDUP", 2.0)
        qps_floor = _env_float("EDL_BENCH_SERVE_QPS_FLOOR", 20.0)
        shed_gate = _env_float("EDL_BENCH_SERVE_SHED_OUTSIDE", 0.01)
        if not res.get("equivalence_ok", False):
            problems.append(
                "coalesced+padded forward was NOT bitwise-identical "
                "to scoring each request alone"
            )
        if res.get("batched_qps", 0.0) < speedup_gate * res.get(
            "unbatched_qps", 0.0
        ):
            problems.append(
                "batched arm %.0f qps < %.1fx the "
                "one-request-per-forward arm's %.0f qps"
                % (
                    res.get("batched_qps", 0.0),
                    speedup_gate,
                    res.get("unbatched_qps", 0.0),
                )
            )
        bursty = res.get("bursty", {})
        if not (0 < bursty.get("p99_ms", -1.0) < p99_gate_ms):
            problems.append(
                "bursty-arm p99 %.0f ms outside the <%.0f ms gate"
                % (bursty.get("p99_ms", -1.0), p99_gate_ms)
            )
        if bursty.get("ok_qps", 0.0) < qps_floor:
            problems.append(
                "bursty arm served %.1f qps, under the %.1f qps floor"
                % (bursty.get("ok_qps", 0.0), qps_floor)
            )
        if bursty.get("shed_rate_outside", 1.0) > shed_gate:
            problems.append(
                "shed rate %.3f OUTSIDE the burst window exceeds "
                "%.3f (%d/%d requests; admission must only shed "
                "under the burst)"
                % (
                    bursty.get("shed_rate_outside", 1.0),
                    shed_gate,
                    bursty.get("shed_outside_burst", -1),
                    bursty.get("n_outside", -1),
                )
            )
        if bursty.get("errors", 1):
            problems.append(
                "%d bursty-arm request(s) errored (only Overloaded "
                "sheds are acceptable there)" % bursty.get("errors", 1)
            )
        if problems:
            print(
                json.dumps(
                    {
                        "metric": "serving_scorer_qps",
                        "error": "; ".join(problems),
                        "detail": res,
                    }
                )
            )
            return 1
        _emit(
            "serving_scorer_qps",
            round(res["qps"], 1),
            "score requests/sec (batch 32) sustained by a %d-process "
            "scorer fleet (micro-batching ON) under LIVE streaming "
            "training churn (train->export->serve loop, "
            "docs/serving.md): p50 %.0f ms, p99 %.0f ms (gate <%.0f "
            "ms), %d ok / %d failed over %.0f s, every scorer "
            "hot-swapped (v%s -> v%s), served-row staleness %s <= "
            "%d-version window scraped via /metrics AFTER a mid-bench "
            "PS shard SIGKILL+snapshot-relaunch (outage %.1f s; "
            "failures confined to it), cache hit rates %s; "
            "micro-batching arms (4-row requests, bitwise-equal to "
            "solo scoring): coalesced %.0f qps vs solo %.0f qps = "
            "%.1fx (gate >=%.1fx, %.1f rows/forward), bursty arm "
            "%.0f->%.0f offered qps served %.1f qps at p99 %.0f ms "
            "with %d burst sheds and %d/%d sheds outside it "
            "(gate <=%.3f)"
            % (
                res["n_scorers"],
                res["p50_ms"],
                res["p99_ms"],
                p99_gate_ms,
                res["requests_ok"],
                res["requests_failed"],
                res["drive_s"],
                res["first_versions"],
                res["final_versions"],
                [round(s, 1) for s in res["staleness"]],
                window,
                res["outage_s"],
                [round(h, 3) for h in res["hit_rates"]],
                res["batched_qps"],
                res["unbatched_qps"],
                res["batch_speedup"],
                speedup_gate,
                res["batched_rows_per_forward"],
                bursty["base_qps_offered"],
                bursty["burst_qps_offered"],
                bursty["ok_qps"],
                bursty["p99_ms"],
                bursty["shed_in_burst"],
                bursty["shed_outside_burst"],
                bursty["n_outside"],
                shed_gate,
            ),
            update,
        )
        return 0

    if "--wire" in argv:
        res = bench_wire(quick)
        _emit(
            "wire_dense_roundtrip_speedup",
            round(res["shm"] / max(res["seed"], 1e-9), 2),
            "x co-located (shm transport) vs seed-codec rounds/sec on "
            "the dense pull+push round, %.1f MiB/direction over real "
            "loopback gRPC (seed %.1f, scatter-gather %.1f [%.2fx], "
            "shm %.1f rounds/s; equivalence pre-pass: identical pulled "
            "params and push sums across arms)"
            % (
                res["payload_mb"],
                res["seed"],
                res["sg"],
                res["sg"] / max(res["seed"], 1e-9),
                res["shm"],
            ),
            update,
        )
        _emit(
            "wire_bf16_ab_speedup",
            round(res["sg_bf16"] / max(res["sg"], 1e-9), 2),
            "x bf16-wire vs f32-wire rounds/sec on the scatter-gather "
            "bytes path (the r5 A/B re-run: 0.82x when compression "
            "paid its own astype pass, now the downcast fuses into "
            "the single frame write and the payload halves; >=1.0x "
            "means compression is no longer a loopback regression)",
            update,
        )
        dev_speedup = res["dev_dlpack"] / max(res["dev_host_staged"], 1e-9)
        if dev_speedup < 1.2:
            print(
                json.dumps(
                    {
                        "metric": "wire_device_frame_speedup",
                        "error": "dlpack device-array frame %.2fx the "
                        "host-staged path — below the 1.2x gate "
                        "(host-staged %.1f r/s, dlpack %.1f r/s at "
                        "%.1f MiB/direction)"
                        % (
                            dev_speedup,
                            res["dev_host_staged"],
                            res["dev_dlpack"],
                            res["dev_payload_mb"],
                        ),
                    }
                )
            )
            return 1
        _emit(
            "wire_device_frame_speedup",
            round(dev_speedup, 2),
            "x dlpack-framed jax.Array vs host-staged frame path on "
            "the co-located (shm) dense pull+push round, %.1f MiB of "
            "device gradients per push (host-staged = the pre-bridge "
            "get_host_state-then-frame shape: owned host copy, then "
            "the frame write — two full-payload passes; the bridge "
            "frames straight out of the device buffer's dlpack view "
            "in one. host-staged %.1f r/s, dlpack %.1f r/s; "
            "equivalence: identical server-observed push sums; "
            "gate >=1.2x)"
            % (
                res["dev_payload_mb"],
                res["dev_host_staged"],
                res["dev_dlpack"],
            ),
            update,
        )
        return 0

    if "--telemetry" in argv:
        res = bench_telemetry(quick)
        overhead = res["overhead_pct"]
        if overhead >= 2.0:
            print(
                json.dumps(
                    {
                        "metric": "telemetry_overhead_pct",
                        "error": "telemetry overhead %.2f%% exceeds the "
                        "2%% budget (median extra CPU vs off-arm wall; "
                        "on %.1f ex/s, off %.1f ex/s)"
                        % (overhead, res["eps_on"], res["eps_off"]),
                    }
                )
            )
            return 1
        _emit(
            "telemetry_overhead_pct",
            round(max(overhead, 0.01), 2),
            "%% input-plane throughput cost of the fully-engaged "
            "telemetry plane (per-batch accounting + snapshot shipping "
            "+ instrumented RPC surface) vs the runtime-disabled arm — "
            "median extra CPU seconds over the off arm's median wall, "
            "the serialized upper bound on the examples/sec cost "
            "(medians: on %.1f ex/s, off %.1f ex/s; gate <2%%). "
            "Live-job check: "
            "master /metrics served per-worker examples/sec, client+"
            "server RPC latency histograms, and task-queue depth "
            "mid-job over real gRPC (%d required families present)"
            % (res["eps_on"], res["eps_off"], res["endpoint_families"]),
            update,
            lower_is_better=True,
        )
        return 0

    if "--trace" in argv:
        res = bench_trace(quick)
        if res["overhead_pct"] >= 2.0:
            print(
                json.dumps(
                    {
                        "metric": "trace_plane_overhead_pct",
                        "error": "tracing overhead %.2f%% exceeds the "
                        "2%% budget (median extra CPU vs off-arm "
                        "wall; on %.1f ex/s, off %.1f ex/s)"
                        % (
                            res["overhead_pct"],
                            res["eps_on"],
                            res["eps_off"],
                        ),
                    }
                )
            )
            return 1
        if res["attribution"] < 0.90:
            print(
                json.dumps(
                    {
                        "metric": "trace_step_attribution",
                        "error": "critical-path breakdown attributes "
                        "only %.1f%% of traced-step wall time to "
                        "named spans over %d steps — below the 90%% "
                        "gate (an uninstrumented step phase is "
                        "eating wall time)"
                        % (100.0 * res["attribution"], res["steps"]),
                    }
                )
            )
            return 1
        _emit(
            "trace_plane_overhead_pct",
            round(max(res["overhead_pct"], 0.01), 2),
            "%% input-plane throughput cost of the fully-engaged "
            "tracing plane (per-batch step spans + child phases, "
            "task/wait+warm+ack spans, wire span-context injection, "
            "pending-buffer shipping) vs the EDL_METRICS-off arm — "
            "median extra CPU over off-arm wall (medians: on %.1f "
            "ex/s, off %.1f ex/s; gate <2%%). Live-job check: /trace "
            "round-tripped through tools/tracetool.py attributed "
            "%.1f%% of %d traced steps' wall time to named spans "
            "(gate >=90%%), and a real SIGKILLed PS shard left a "
            "parseable %d-line flight-recorder postmortem"
            % (
                res["eps_on"],
                res["eps_off"],
                100.0 * res["attribution"],
                res["steps"],
                res["postmortem_lines"],
            ),
            update,
            lower_is_better=True,
        )
        return 0

    if "--input" in argv:
        res = bench_input(quick)
        _emit(
            "input_examples_per_sec_pipelined"
            + ("_quick" if quick else ""),
            round(res["pipelined"], 1),
            "examples/sec through the pipelined worker input plane "
            "(task_prefetch=2, map x4 ordered decode, vectorized batch, "
            "queued acks) vs %.1f ex/s through the serial plane "
            "(pipelined %.2fx; both arms on the real task data service "
            "with %.0f ms injected get_task RTT and %.0f us injected "
            "per-record read latency; equivalence pre-pass: identical "
            "batches, identical order)"
            % (
                res["serial"],
                res["pipelined"] / max(res["serial"], 1e-9),
                res["rtt_ms"],
                res["read_lat_us"],
            ),
            update,
        )
        return 0

    if "--a2a-dedup" in argv:
        cpu = not quick and _on_cpu()
        res = bench_a2a_dedup(quick)
        _emit(
            "hbm_embedding_a2a_dedup_rows_per_sec"
            + ("_quick" if quick else "_cpu" if cpu else ""),
            round(res["dedup"], 0),
            "rows/sec fwd+bwd (%s; naive per-occurrence routing "
            "%.2fM rows/s, dedup %.2fx)"
            % (
                res["_desc"],
                res["naive"] / 1e6,
                res["dedup"] / max(res["naive"], 1e-9),
            ),
            update,
        )
        return 0

    if "--preemption-ratio" in argv:
        res = bench_preemption()
        ratio = res["killed_s"] / max(res["clean_s"], 1e-9)
        # the RATIO ratchets: absolute seconds swing ~2x with host load
        # (BASELINE.md r3), killed/clean cancels that out. Lower is
        # better; lower_is_better inverts vs_baseline so >1 still
        # reads as an improvement like every other suite metric.
        _emit(
            "elastic_preemption_ratio",
            round(ratio, 2),
            "x killed/clean wall-clock, 3-proc elastic job, 1 SIGKILL "
            "(clean %.1fs, killed %.1fs, overhead %.1fs; lower=better)"
            % (
                res["clean_s"],
                res["killed_s"],
                res["killed_s"] - res["clean_s"],
            ),
            update,
            lower_is_better=True,
        )
        return 0

    if "--preemption" in argv:
        res = bench_preemption()
        print(
            json.dumps(
                {
                    "metric": "elastic_job_wallclock_under_kill",
                    "value": res["killed_s"],
                    "unit": "seconds (vs %.1fs same-config clean run: "
                    "kill overhead %.1fs, %.2fx clean)"
                    % (
                        res["clean_s"],
                        res["killed_s"] - res["clean_s"],
                        res["killed_s"] / max(res["clean_s"], 1e-9),
                    ),
                    "vs_baseline": 1.0,
                }
            )
        )
        return 0

    if "--e2e" in argv:
        eps = bench_e2e(quick)
        print(
            json.dumps(
                {
                    "metric": "resnet50_e2e_examples_per_sec_per_chip",
                    "value": round(eps, 2),
                    "unit": "examples/sec/chip (EDLR file -> Dataset -> step)",
                    "vs_baseline": 1.0,
                }
            )
        )
        return 0

    profile_dir = None
    if "--profile" in argv:
        idx = argv.index("--profile")
        if idx + 1 >= len(argv) or argv[idx + 1].startswith("-"):
            print(
                json.dumps(
                    {"error": "--profile requires a directory argument"}
                )
            )
            return 2
        profile_dir = argv[idx + 1]

    if "--resnet" in argv or quick:
        # single-metric mode (the pre-r5 default; --quick keeps it so
        # smoke runs stay fast)
        cpu = not quick and _on_cpu()
        try:
            eps = bench_resnet(quick, profile_dir)
        except RuntimeError as e:
            # keep the one-JSON-line contract even on divergence
            print(json.dumps({"error": str(e)}))
            return 1
        _emit(
            "resnet50_examples_per_sec_per_chip"
            + ("_quick" if quick else "_cpu" if cpu else ""),
            round(eps, 2),
            "examples/sec/chip",
            update,
        )
        return 0

    # Default: the compact ratcheted suite — one JSON line per headline
    # metric, each vs its BASELINE.json ratchet, so a regression in the
    # kernel, the compute path, or the elastic plane fails loudly in the
    # per-round driver capture instead of only when that mode is
    # hand-run (VERDICT r4 weak #1). Every section runs as a SUBPROCESS
    # with a hard timeout: a wedged accelerator transport hangs C++
    # device calls forever, and an in-process hang would take the whole
    # capture down with it. Ordering and budget (VERDICT r5 weak #1):
    # CPU-only sections (--preemption-ratio, --ps) run FIRST so a dead
    # accelerator can never starve the sections that don't need one; a
    # GLOBAL budget (EDL_BENCH_TOTAL_BUDGET, default 3600s) clamps every
    # section's timeout to the time left so the suite always finishes
    # inside the driver's capture window; and the FIRST device-section
    # timeout issues an early wedge verdict that skips the remaining
    # device sections instead of timing each one out in turn.
    failures = 0
    me = os.path.abspath(__file__)
    device_wedged = False
    # default sized to finish inside the driver's capture window with
    # headroom (BENCH_r05 rc=124: the old 3600 default outlived the
    # window once CPU-priced device sections started eating their full
    # per-section timeouts); raise via env for a real-accelerator run
    try:
        total_budget = float(
            os.environ.get("EDL_BENCH_TOTAL_BUDGET", "1500")
        )
    except ValueError:
        total_budget = 1500.0
    t_suite = time.monotonic()

    # concurrency gate first: a dirty edlint tree withholds every
    # speedup metric below (each section subprocess re-checks too),
    # so the suite fails loudly instead of publishing tainted wins
    if _edlint_regressed():
        failures += 1
        print(
            json.dumps(
                {
                    "metric": "edlint_gate",
                    "error": "%d violation(s): speedup metrics "
                    "withheld this run" % _edlint_regressed(),
                }
            )
        )

    def section(name, flags, timeout, device=False):
        nonlocal failures, device_wedged
        try:
            timeout = int(
                os.environ.get("EDL_BENCH_SECTION_TIMEOUT", timeout)
            )
        except ValueError:
            pass  # malformed override: keep the per-section default
        if device and device_wedged:
            failures += 1
            print(
                json.dumps(
                    {
                        "metric": name,
                        "error": "skipped: early wedge verdict "
                        "(device transport already hung a section)",
                    }
                )
            )
            return
        left = total_budget - (time.monotonic() - t_suite)
        if left < 60:
            failures += 1
            print(
                json.dumps(
                    {
                        "metric": name,
                        "error": "skipped: global bench budget "
                        "(%ds) exhausted" % int(total_budget),
                    }
                )
            )
            return
        budget_clamped = left < timeout
        timeout = min(timeout, int(left))
        cmd = [sys.executable, me] + flags
        if update:
            cmd.append("--update-baseline")
        rc, stdout, stderr, timed_out = _run_section_cmd(cmd, timeout)
        if timed_out:
            failures += 1
            # metrics the section emitted BEFORE the kill are real
            # measurements — flush them so a wedge late in a section
            # does not discard the evidence gathered ahead of it (the
            # partial stdout used to be dropped on the floor here)
            flushed = 0
            for line in stdout.splitlines():
                try:
                    json.loads(line)
                except ValueError:
                    continue
                print(line)
                flushed += 1
            # a budget-clamped timeout is NOT evidence of a wedge — a
            # healthy-but-slow section that lost most of its window to
            # the budget must not condemn the remaining device sections
            if device and not device_wedged and not budget_clamped:
                device_wedged = True
                print(
                    json.dumps(
                        {
                            "metric": "bench_wedge_verdict",
                            "section": name,
                            "timeout_s": timeout,
                            "metrics_flushed": flushed,
                            "error": "device transport wedged: "
                            "section %s hung past %ds; skipping the "
                            "remaining device sections" % (name, timeout),
                        }
                    )
                )
            print(
                json.dumps(
                    {
                        "metric": name,
                        "section": name,
                        "timed_out_after_s": timeout,
                        "metrics_flushed": flushed,
                        "error": "section timed out after %ds "
                        "(wedged device transport?)" % timeout,
                    }
                )
            )
            return
        emitted = False
        for line in stdout.splitlines():
            try:
                json.loads(line)
            except ValueError:
                continue
            print(line)
            emitted = True
        if rc != 0 or not emitted:
            failures += 1
            if not emitted:
                print(
                    json.dumps(
                        {
                            "metric": name,
                            "error": (stderr or stdout)[-400:],
                        }
                    )
                )

    resnet_flags = ["--resnet"]
    if profile_dir:
        # keep the documented `bench.py --profile DIR` tracing working
        # in suite mode (the resnet section owns the trace)
        resnet_flags += ["--profile", profile_dir]
    # CPU-only sections first: they need no accelerator and must never
    # starve behind a wedged one
    section("elastic_preemption_ratio", ["--preemption-ratio"], 900)
    section("input_examples_per_sec_pipelined", ["--input"], 300)
    section("telemetry_overhead_pct", ["--telemetry"], 600)
    section("trace_plane_overhead_pct", ["--trace"], 600)
    section("compile_cached_establish_speedup", ["--compile"], 600)
    # the layout re-solve gates (ISSUE 20): planned-vs-cold resize
    # pause ceiling + solver-vs-naive throughput floor, CPU mesh
    section("resize_layout_speculative_pause_ratio", ["--resize"], 600)
    section("wire_dense_roundtrip_speedup", ["--wire"], 300)
    section("sharded_dense_examples_per_sec", ["--sharded"], 600)
    section("ps_deepfm_examples_per_sec", ["--ps"], 900)
    # the tiered-store gate: bitwise equivalence vs the all-in-memory
    # shard, then the throughput floor with the disk tier provably
    # exercised (docs/tiered_store.md)
    section("ps_tiered_examples_per_sec", ["--tiered"], 900)
    section("ps_deepfm_examples_per_sec_hybrid", ["--hybrid"], 900)
    # the recovery-plane gates: SIGKILL one PS shard mid-job under a
    # snapshot cadence (docs/ps_recovery.md) AND SIGKILL the MASTER
    # mid-job under the dispatch journal (docs/master_recovery.md);
    # both jobs must complete — restored shard state within the
    # snapshot-staleness bound, master-kill accounting exactly-once
    # with the final state inside the fault-free noise floor
    section("ps_chaos_recovery_divergence", ["--chaos"], 750)
    # the serving-plane gate: a 2-process scorer fleet under live
    # streaming training churn, p99 + staleness-bound + hot-swap +
    # shard-kill-recovery gates (docs/serving.md)
    section("serving_scorer_qps", ["--serve"], 900)
    # device sections, cheapest diagnosis first (each shrinks its
    # workload and renames its metric _cpu when the backend is plain
    # CPU, so the suite fits the budget without an accelerator)
    section(
        "resnet50_examples_per_sec_per_chip",
        resnet_flags,
        600,
        device=True,
    )
    section(
        "transformer_lm_tokens_per_sec_per_chip",
        ["--transformer"],
        600,
        device=True,
    )
    section(
        "flash_attention_speedup_l2048",
        ["--flash", "--l2048"],
        600,
        device=True,
    )
    section(
        "hbm_embedding_a2a_dedup_rows_per_sec",
        ["--a2a-dedup"],
        600,
        device=True,
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
