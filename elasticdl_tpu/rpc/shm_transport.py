"""Shared-memory transport for co-located PS pods (docs/wire.md).

On a loopback fleet (PS pods scheduled on the worker's host — the
co-located placement k8s topology hints produce for exactly this
reason) the gRPC payload path still pays serialization into a `bytes`
request, the C-core's own copies, and the receive-side reassembly.
This module moves the PAYLOAD into a client-owned ring of
``multiprocessing.shared_memory`` slots negotiated at connect time via
a ``transport_hello`` RPC; the gRPC message then carries only
``{segment name, slot, generation, length}``, ~100 bytes regardless of
tensor sizes. The scatter-gather packer (rpc/core.plan_message /
pack_message_into) writes frames STRAIGHT into the slot — one memcpy
from the source arrays into shared memory per direction, zero
intermediate `bytes` — and the receiver decodes read-only views in
place (common/tensor deserialization contract).

Protocol:

- ``transport_hello``: the client creates a ring (one per channel) and
  sends ``{name, n_slots, slot_size, host}``; the server attaches only
  when the host fingerprint (hostname + kernel boot id) matches its
  own and the attach succeeds — anything else answers
  ``accepted=False`` and the channel permanently falls back to the
  bytes path. The ring is REQUEST AND RESPONSE transport: the server
  overwrites the request slot with its reply (the slot stays
  client-owned for the whole round trip).
- Each slot carries a 16-byte header ``(u64 generation, u64 length)``.
  The client stamps a fresh generation per call; the server validates
  it before dispatch and stamps ``generation | RESP_BIT`` on the
  reply, so a retried control RPC can never decode a response as a
  request (it reads a mismatch and answers ``_shm_error`` WITHOUT
  dispatching — the retry then goes inline, which is safe exactly
  because nothing was dispatched).
- Fallbacks are per-call and lossless: payload too big for a slot or
  slot pool exhausted -> inline bytes path; ``_shm_error`` (server
  restarted, ring unknown) -> channel disables itself and resends
  inline; transport error mid-call (deadline on a dead pod) ->
  the slot is QUARANTINED, never reused, because the server might
  still write into it after the client moved on.
- Lifetime: the creator unlinks on ``close()`` and at interpreter
  exit (atexit); the server's registry unlinks every attached ring on
  ``close()``, which is what reclaims segments of clients that were
  SIGKILLed mid-call (POSIX keeps /dev/shm names until someone
  unlinks; the memory itself dies with the last mapping).

Slot replies decode with a :class:`~elasticdl_tpu.common.tensor.
WireArena` whose ``release()`` recycles the slot — consumers
(worker/ps_client.py) materialize anything they retain, then release.
"""

import atexit
import socket
import struct
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.tensor import WireArena

_NAME_PREFIX = "edlw-"
_SLOT_HDR = 16  # u64 generation | u64 payload length
_RESP_BIT = 1 << 62  # stamped into the generation of a reply header
_MAX_SLOTS = 64
_MAX_SLOT_BYTES = 256 << 20
_MAX_RING_BYTES = 1 << 30


def host_fingerprint():
    """Identity of this kernel + hostname: equal fingerprints mean the
    peers can plausibly see the same /dev/shm namespace (a mismatching
    container mount namespace still fails at attach, which the hello
    treats the same way: bytes-path fallback)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = ""
    return "%s|%s" % (socket.gethostname(), boot)


class ShmRing:
    """A fixed-geometry ring of payload slots in one shared segment.

    Created (and owned) by the CLIENT; the server attaches by name.
    All slot bookkeeping beyond the 16-byte in-segment headers lives on
    the client side, so the segment itself needs no cross-process
    synchronization — a slot is exclusively the client's except during
    the window between sending the control RPC and receiving its
    reply, when it is exclusively the server's."""

    def __init__(self, n_slots, slot_size, name=None):
        from multiprocessing import shared_memory

        self.n_slots = int(n_slots)
        self.slot_size = int(slot_size)
        self._stride = _SLOT_HDR + self.slot_size
        size = self._stride * self.n_slots
        self.created = name is None
        if self.created:
            import uuid

            for _attempt in range(8):
                candidate = _NAME_PREFIX + uuid.uuid4().hex[:16]
                try:
                    self._shm = shared_memory.SharedMemory(
                        name=candidate, create=True, size=size
                    )
                    break
                except FileExistsError:
                    continue
            else:
                raise OSError("could not allocate a unique shm ring name")
        else:
            if not name.startswith(_NAME_PREFIX):
                raise ValueError("not an elasticdl wire segment: %r" % name)
            self._shm = shared_memory.SharedMemory(name=name)
            if self._shm.size < size:
                self._shm.close()
                raise ValueError("segment smaller than advertised ring")
            # CPython < 3.13 registers ATTACHED segments with the
            # resource tracker too, which would unlink the creator's
            # live segment when this (server) process exits — detach
            # the tracker, the creator owns the name (_dispose
            # re-balances the ledger before any unlink)
            self._tracker_call("unregister")
        self.name = self._shm.name
        self._destroyed = False

    def _tracker_call(self, op):
        from multiprocessing import resource_tracker

        try:
            getattr(resource_tracker, op)(self._shm._name, "shared_memory")
            return True
        except (AttributeError, KeyError, ValueError, OSError) as err:
            logger.debug("shm resource-tracker %s skipped: %s", op, err)
            return False

    def payload_view(self, slot):
        """Writable memoryview of one slot's payload area."""
        base = slot * self._stride + _SLOT_HDR
        return self._shm.buf[base : base + self.slot_size]

    def write_header(self, slot, generation, length):
        struct.pack_into(
            "<QQ", self._shm.buf, slot * self._stride, generation, length
        )

    def read_header(self, slot):
        return struct.unpack_from("<QQ", self._shm.buf, slot * self._stride)

    def _dispose(self, unlink):
        if self._destroyed:
            return
        self._destroyed = True
        if unlink:
            # balance the tracker ledger BEFORE unlink: the attach-time
            # detach (and same-process create+attach topologies —
            # tests, the loopback bench — where the set-backed ledger
            # collapses the two registrations into one) can leave this
            # name untracked, and unlink()'s built-in unregister would
            # then crash the tracker's exit sweep. register is a
            # set-add: always safe, leaves exactly one entry for
            # unlink to consume.
            self._tracker_call("register")
            try:
                self._shm.unlink()
            except FileNotFoundError:
                # the peer unlinked first; drop our (now dangling)
                # tracker entry so exit-time cleanup stays silent
                self._tracker_call("unregister")
        try:
            self._shm.close()
        except BufferError:
            # numpy views into the segment are still alive somewhere;
            # the mapping dies with the process, and the name is
            # already gone above — nothing can leak
            logger.debug(
                "shm ring %s close deferred: exported views still live",
                self.name,
            )

    def destroy(self):
        """Close this mapping; unlink the name if we created it.

        Unlink only removes the /dev/shm NAME — the memory lives until
        the last mapping drops, so a consumer still holding
        un-materialized views keeps valid pages and the OS reclaims at
        process exit."""
        self._dispose(unlink=self.created)

    def reclaim(self):
        """Server-side reclamation of a (possibly dead) client's ring:
        unlink the name regardless of who created it, then close —
        the path that frees segments of SIGKILLed clients."""
        self._dispose(unlink=True)


class ShmChannel:
    """Client-side channel: an rpc.core ``Client`` plus the negotiated
    shared-memory payload path, with per-call bytes-path fallback.

    Thread-safe for the PSClient fan-out pool: slot accounting rides
    one lock; the RPCs themselves always run outside it. Retry safety
    matches the PR-2 invariants — the control RPC for ``method`` is
    retriable exactly when ``method`` is idempotent, and every
    ``_shm_error`` reply is answered by the server BEFORE dispatch, so
    the inline resend it triggers can never double-apply."""

    def __init__(self, client, n_slots=4, slot_mb=8):
        self._client = client
        self._n_slots = max(1, int(n_slots))
        self._slot_size = max(1, int(slot_mb)) << 20
        self._mu = threading.Lock()
        self._state = "new"  # new | negotiating | on | off
        self._ring = None
        self._free = list(range(self._n_slots))
        self._gen = 0
        # calls currently between _acquire and _leave: a concurrent
        # _disable (peer _shm_error, close()) must not destroy the
        # ring out from under them — it parks it in _retired instead
        self._users = 0
        self._retired = None
        self.stats = {"shm": 0, "inline": 0, "quarantined": 0}

    # -- negotiation ----------------------------------------------------

    def _ensure(self):
        """Current state, driving the one-shot hello on first use.

        Exactly one thread claims the negotiation; the RPC runs outside
        the lock (edlint R5), and racers use the inline path until the
        state settles."""
        with self._mu:
            if self._state != "new":
                return self._state
            self._state = "negotiating"
        state, ring = "off", None
        try:
            ring = ShmRing(self._n_slots, self._slot_size)
            atexit.register(ring.destroy)  # crash-safe unlink floor
            resp = self._client.call(
                "transport_hello",
                name=ring.name,
                n_slots=self._n_slots,
                slot_size=self._slot_size,
                host=host_fingerprint(),
            )
            if resp.get("accepted"):
                state = "on"
            else:
                logger.info(
                    "shm transport declined (%s); using the bytes path",
                    resp.get("reason", "unspecified"),
                )
        except Exception as err:  # noqa: BLE001 — any failure => bytes path
            logger.info(
                "shm transport negotiation failed (%s); using the "
                "bytes path",
                err,
            )
        if state != "on" and ring is not None:
            ring.destroy()
            ring = None
        with self._mu:
            self._ring = ring
            self._state = state
        return state

    # -- slot accounting ------------------------------------------------

    def _acquire(self):
        """(ring, slot, generation) or None when the pool is empty or
        the channel is not (yet) on. A successful claim counts the
        caller as a ring user until its matching :meth:`_leave`."""
        with self._mu:
            if self._state != "on" or not self._free:
                return None
            slot = self._free.pop()
            self._gen += 1
            self._users += 1
            return self._ring, slot, self._gen

    def _leave(self):
        """The caller is done touching ring memory (its reply views,
        if any, keep their own mapping alive); the last user out
        destroys a ring a concurrent _disable retired."""
        with self._mu:
            self._users -= 1
            ring = None
            if self._users == 0 and self._retired is not None:
                ring, self._retired = self._retired, None
        if ring is not None:
            ring.destroy()

    def _release(self, slot):
        with self._mu:
            if self._state == "on" and slot not in self._free:
                self._free.append(slot)

    def _quarantine(self, slot):
        """Never reuse ``slot``: after a transport error mid-call the
        server may still write its late reply into it, and a fresh
        request there could be torn under that write. Slots are cheap;
        a channel that loses all of them degrades to the bytes path."""
        with self._mu:
            self.stats["quarantined"] += 1

    def _disable(self):
        """Stop offering shm on this channel. The ring is destroyed
        only once no call is between _acquire and _leave — a fan-out
        sibling mid-call must degrade to the bytes path, not crash on
        a closed mapping."""
        with self._mu:
            self._state = "off"
            ring, self._ring = self._ring, None
            if ring is not None and self._users:
                self._retired, ring = ring, None
        if ring is not None:
            ring.destroy()

    # -- the call path --------------------------------------------------

    def _inline(self, method, fields, plan=None):
        """The bytes path, with the PR-2 retry guard computed in ONE
        place; an already-built plan rides through so fallbacks never
        plan a message twice."""
        with self._mu:
            self.stats["inline"] += 1
        return self._client.call(
            method,
            _retriable=(method != "push_gradient"),
            _plan=plan,
            **fields
        )

    def call(self, method, /, **fields):
        # positional-only: a wire field may itself be named "method"
        # (get_model's GetModelMethod selector) and must land in
        # ``fields``, not collide with the RPC name
        from elasticdl_tpu.rpc.core import (
            pack_message_into,
            plan_message,
            unpack_message,
        )
        from elasticdl_tpu.utils import profiling

        # span context rides the SLOT payload (the control message only
        # carries the slot spec), so inject before planning; the inline
        # fallbacks reuse these fields and Client.call skips its own
        # injection when the key is already present
        sctx = profiling.wire_span_context()
        if sctx is not None and "_sctx" not in fields:
            fields["_sctx"] = sctx

        if self._ensure() != "on":
            return self._inline(method, fields)
        plan = plan_message(fields)
        claim = self._acquire() if plan.total <= self._slot_size else None
        if claim is None:
            # payload bigger than a slot, or every slot in flight /
            # quarantined: the bytes path is always correct
            return self._inline(method, fields, plan)
        ring, slot, gen = claim
        try:
            payload = ring.payload_view(slot)
            pack_message_into(plan, payload)
            ring.write_header(slot, gen, plan.total)
            try:
                ctrl = self._client.call(
                    method,
                    _retriable=(method != "push_gradient"),
                    _shm_req={
                        "name": ring.name,
                        "slot": slot,
                        "gen": gen,
                        "len": plan.total,
                    },
                )
            except BaseException:
                self._quarantine(slot)
                raise
            if "_shm_error" in ctrl:
                # answered BEFORE dispatch (ring unknown / stale
                # generation — e.g. a restarted PS lost its
                # attachments): resend inline, and stop offering shm
                # on this channel
                logger.warning(
                    "shm transport rejected by server (%s); falling "
                    "back to the bytes path",
                    ctrl["_shm_error"],
                )
                self._release(slot)
                self._disable()
                return self._inline(method, fields, plan)
            spec = ctrl.get("_shm_resp")
            if spec is None:
                # reply didn't fit a slot: it arrived inline, slot done
                self._release(slot)
                with self._mu:
                    self.stats["shm"] += 1
                return ctrl
            hgen, hlen = ring.read_header(slot)
            if spec.get("gen") != gen or hgen != (gen | _RESP_BIT) or (
                hlen != spec.get("len")
            ):
                self._quarantine(slot)
                self._disable()
                raise RuntimeError(
                    "shm reply generation mismatch on %s slot %d "
                    "(protocol desync; channel disabled)"
                    % (ring.name, slot)
                )
            view = payload[: spec["len"]].toreadonly()
            arena = WireArena(view, on_release=lambda: self._release(slot))
            with self._mu:
                self.stats["shm"] += 1
            return unpack_message(view, arena=arena)
        finally:
            # reply views (if any) hold their own mapping; this only
            # ends the window where ring HEADERS/slots may be touched,
            # letting a concurrent _disable's deferred destroy proceed
            self._leave()

    def close(self):
        self._disable()

    @property
    def state(self):
        with self._mu:
            return self._state


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class ShmEndpointRegistry:
    """Server-side table of client rings attached via transport_hello.

    ``close()`` reclaims EVERY attached ring (unlink + close) — the
    path that frees segments of clients SIGKILLed mid-call, since a
    dead creator's atexit never ran."""

    def __init__(self, writable_request_views=False):
        self._mu = threading.Lock()
        self._rings = {}
        self._fingerprint = host_fingerprint()
        # device-resident PS shards opt in (docs/ps_device.md): request
        # payloads decode as WRITABLE slot views so gradients can
        # dlpack-import to device with zero copies (numpy cannot export
        # a read-only buffer). Safe under the existing slot contract —
        # the handler consumes the request fully before the reply
        # overwrites the slot (the device apply blocks on its outputs)
        # — but it forfeits the codec's mutation guard, so it is never
        # the default.
        self._writable_request_views = bool(writable_request_views)

    def hello(self, req):
        name = req.get("name", "")
        n_slots = int(req.get("n_slots", 0))
        slot_size = int(req.get("slot_size", 0))
        if req.get("host") != self._fingerprint:
            return {"accepted": False, "reason": "cross-host"}
        if not isinstance(name, str) or not name.lstrip("/").startswith(
            _NAME_PREFIX
        ):
            return {"accepted": False, "reason": "bad segment name"}
        if not (
            0 < n_slots <= _MAX_SLOTS
            and 0 < slot_size <= _MAX_SLOT_BYTES
            and n_slots * slot_size <= _MAX_RING_BYTES
        ):
            return {"accepted": False, "reason": "ring geometry out of bounds"}
        try:
            ring = ShmRing(n_slots, slot_size, name=name)
        except (OSError, ValueError) as err:
            return {"accepted": False, "reason": "attach failed: %s" % err}
        with self._mu:
            old = self._rings.pop(name, None)
            self._rings[name] = ring
        if old is not None:
            old.reclaim()  # same client re-negotiated: the old attach goes
        return {"accepted": True}

    def _resolve(self, name):
        with self._mu:
            return self._rings.get(name)

    def wrap(self, fn):
        """Route ``_shm_req`` control messages through the slot; plain
        requests pass straight to ``fn``. Every ``_shm_error`` return
        happens BEFORE ``fn`` runs (the client's inline resend safety).
        """
        from elasticdl_tpu.rpc.core import (
            pack_message_into,
            plan_message,
            unpack_message,
        )

        def handler(req):
            spec = req.get("_shm_req") if isinstance(req, dict) else None
            if spec is None:
                return fn(req)
            ring = self._resolve(spec.get("name", ""))
            if ring is None:
                return {"_shm_error": "unknown ring"}
            slot, gen = int(spec.get("slot", -1)), int(spec.get("gen", -1))
            length = int(spec.get("len", -1))
            if not 0 <= slot < ring.n_slots:
                return {"_shm_error": "slot out of range"}
            hgen, hlen = ring.read_header(slot)
            if hgen != gen or hlen != length or not (
                0 <= length <= ring.slot_size
            ):
                return {"_shm_error": "stale generation"}
            payload = ring.payload_view(slot)
            if self._writable_request_views:
                request = unpack_message(
                    payload[:length], writable=True
                )
            else:
                request = unpack_message(payload[:length].toreadonly())
            reply = fn(request) or {}
            # the handler is done with the request (the audited PS
            # servicer materializes anything it retains), so the slot
            # can carry the reply back in place
            del request
            plan = plan_message(reply)
            if plan.total > ring.slot_size:
                return reply  # inline fallback for oversized replies
            pack_message_into(plan, payload)
            ring.write_header(slot, gen | _RESP_BIT, plan.total)
            return {
                "_shm_resp": {"slot": slot, "gen": gen, "len": plan.total}
            }

        return handler

    def close(self):
        with self._mu:
            rings, self._rings = list(self._rings.values()), {}
        for ring in rings:
            ring.reclaim()


def install_shm_endpoint(
    methods, hello_extra=None, writable_request_views=False
):
    """Wrap a ``{name: fn}`` RPC table with the shared-memory endpoint.

    Returns ``(methods, registry)`` where ``methods`` additionally
    serves ``transport_hello``; call ``registry.close()`` at server
    stop to reclaim attached (including orphaned) rings.

    ``hello_extra``: extra fields merged into every hello reply —
    the PS serves its ``shard_epoch`` boot id here so a reconnecting
    co-located client learns the incarnation at negotiation time,
    before its first data-plane round (docs/ps_recovery.md).

    ``writable_request_views``: device-resident PS shards only — see
    :class:`ShmEndpointRegistry`."""
    registry = ShmEndpointRegistry(
        writable_request_views=writable_request_views
    )
    wrapped = {name: registry.wrap(fn) for name, fn in methods.items()}
    if hello_extra:
        extra = dict(hello_extra)

        def hello(req):
            resp = dict(registry.hello(req) or {})
            resp.update(extra)
            return resp

        wrapped["transport_hello"] = hello
    else:
        wrapped["transport_hello"] = registry.hello
    return wrapped, registry
