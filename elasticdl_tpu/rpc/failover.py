"""Master-channel failover: survive a master restart, not just a busy one.

The control-plane invariant since PR 2 was "a Master* class never passes
``deadline_s``/``retries`` — the channel blocks by design" (edlint R9):
a worker parked on ``get_task`` against a busy master must wait, not
error. That invariant said nothing about a DEAD master, and before the
master recovery plane a dead master was unsurvivable anyway — every
blocking call surfaced UNAVAILABLE and the worker died with it.

:class:`MasterFailoverChannel` is the ONE audited place the master
channel carries retry behavior (the R9 invariant is now "only through
the failover-mode wrapper"). Semantics:

- **Busy-master blocking preserved.** Attempts carry no deadline by
  default (``attempt_deadline_s=0``): a slow reply still blocks, so the
  historical contract holds. A finite attempt deadline is opt-in for
  deployments where a vanished pod black-holes SYNs instead of
  refusing them; DEADLINE_EXCEEDED is NEVER retried (a timed-out
  ``get_task`` whose dispatch the live master processed would leak that
  task in the doing-set — PR-2's reasoning, unchanged).
- **UNAVAILABLE rides out the outage.** Connection refused / reset is
  the shape a SIGKILLed-and-relaunching master presents; the wrapper
  retries with doubling, capped backoff until ``outage_budget_s`` is
  spent, then raises. The control-plane reads are pure, and the write
  whose exactly-once-ness the job's ACCOUNTING depends on —
  ``report_task_result`` — is deduplicated by the new master against
  its journal by the ack's (trace_id, attempt). The one resend that
  is NOT deduped: ``report_gradient`` against a master-KV master,
  where a connection reset between the apply and the reply can land
  one gradient twice — the same bounded-SSP-noise class as the PS
  plane's drain-time drops (async mode already tolerates stale and
  lost updates inside the window; docs/master_recovery.md). PS-mode
  fleets never route gradients through this channel.
- **Epoch detection.** Every master reply carries the serving
  incarnation's ``master_epoch`` boot id (the ``shard_epoch`` pattern);
  the wrapper watches it and fires ``on_epoch_change(old, new)`` once
  per transition so the owner (MasterClient) can run its reconnect
  protocol — re-register membership, re-push a first-write-wins model
  to a master-KV incarnation that lost it.

``outage_budget_s=0`` disables the retry loop entirely (single attempt,
raise as before) while keeping the epoch watch — the wrapper is then a
pure pass-through, which is why MasterClient always routes through it.
"""

import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.utils import profiling

# backoff shape for the outage retry loop: doubling from _BACKOFF_S,
# capped — a fleet of workers hammering a booting master helps nobody,
# and the master's journal replay is itself part of the outage window
_BACKOFF_S = 0.2
_BACKOFF_CAP_S = 2.0


class MasterFailoverChannel:
    """``call``-compatible wrapper around one ``rpc.core.Client``.

    The audited R9 exemption: this class alone may hand the master
    channel's Client a deadline, and its retry loop alone may resend a
    master RPC — see the module docstring for why each is safe.
    """

    def __init__(
        self,
        addr,
        outage_budget_s=0.0,
        attempt_deadline_s=0.0,
        on_epoch_change=None,
    ):
        from elasticdl_tpu.rpc.core import Client

        self._addr = addr
        self._attempt_deadline = (
            attempt_deadline_s if attempt_deadline_s > 0 else None
        )
        self._client = Client(addr, deadline_s=self._attempt_deadline)
        self._budget_s = max(0.0, float(outage_budget_s))
        self._on_epoch_change = on_epoch_change
        self._mu = threading.Lock()
        self._epoch = None  # last master_epoch observed in any reply
        self._outage_logged = False
        self._c_retries = profiling.metrics.counter(
            "edl_master_failover_retries_total",
            "Master-channel calls resent through an outage window",
            labels=("method",),
        )

    @property
    def master_epoch(self):
        with self._mu:
            return self._epoch

    @property
    def outage_budget_s(self):
        return self._budget_s

    def call(self, rpc_name, _retriable=True, _budget_s=None, **fields):
        """One logical master RPC, resent through an UNAVAILABLE window.

        ``_budget_s`` overrides the channel's outage budget for this
        call (telemetry shipping caps its own so a worker draining at
        job end never parks behind a master that already exited).
        """
        import grpc

        budget = self._budget_s if _budget_s is None else _budget_s
        deadline = (
            time.monotonic() + budget if budget > 0 else None
        )
        backoff = _BACKOFF_S
        failures = 0
        while True:
            try:
                # the inner client never retries itself (retries=0) —
                # THIS loop owns resend policy; the guard keeps the R9
                # dynamic-dispatch invariant visible at the call site
                resp = self._client.call(
                    rpc_name,
                    _retriable=(rpc_name != "push_gradient"),
                    **fields,
                )
            except grpc.RpcError as err:
                code = (
                    err.code()
                    if callable(getattr(err, "code", None))
                    else None
                )
                retriable = (
                    _retriable
                    and code == grpc.StatusCode.UNAVAILABLE
                    and deadline is not None
                    and time.monotonic() + backoff < deadline
                )
                if not retriable:
                    raise
                self._note_outage(rpc_name)
                self._c_retries.inc(method=rpc_name)
                failures += 1
                if failures % 2 == 0:
                    # gRPC parks a failed subchannel in
                    # TRANSIENT_FAILURE under its OWN exponential
                    # reconnect backoff (up to ~2 min) — longer than
                    # the whole relaunch window, so retrying on the
                    # same channel can spin against a cached failure
                    # while the new master is already serving. A fresh
                    # channel dials immediately.
                    self._reconnect()
                time.sleep(backoff)
                backoff = min(backoff * 2, _BACKOFF_CAP_S)
                continue
            self.note_reply(resp)
            return resp

    def _reconnect(self):
        """Swap in a fresh channel; the old one is DROPPED, not closed:
        a concurrent call on another thread may still be blocked on it,
        and grpc raises a non-RpcError ValueError on a closed channel —
        which would escape every caller's retry machinery. The retired
        channel's resources free when its last in-flight call drops the
        reference (GC-closed; outage-bounded churn)."""
        from elasticdl_tpu.rpc.core import Client

        self._client = Client(
            self._addr, deadline_s=self._attempt_deadline
        )

    def _note_outage(self, rpc_name):
        with self._mu:
            first = not self._outage_logged
            self._outage_logged = True
        if first:
            logger.warning(
                "master unreachable (%s); retrying through the outage "
                "window with capped backoff",
                rpc_name,
            )
            profiling.events.emit("master_unavailable", method=rpc_name)

    def note_reply(self, resp):
        """Watch ``master_epoch`` in a decoded reply. Public because
        shm-slot replies decode OUTSIDE this channel (the control reply
        only carries the slot spec) and the owner hands them back in."""
        epoch = None
        if isinstance(resp, dict):
            epoch = resp.get("master_epoch")
        changed = None
        with self._mu:
            self._outage_logged = False
            if epoch is not None and epoch != self._epoch:
                changed = (self._epoch, epoch)
                self._epoch = epoch
        if changed is not None and changed[0] is not None:
            logger.warning(
                "master epoch changed %s -> %s: a relaunched master is "
                "serving; running the reconnect protocol",
                changed[0],
                changed[1],
            )
            profiling.events.emit(
                "master_epoch_change",
                old=changed[0],
                new=changed[1],
            )
            if self._on_epoch_change is not None:
                try:
                    self._on_epoch_change(changed[0], changed[1])
                except Exception:
                    logger.warning(
                        "master epoch-change hook failed", exc_info=True
                    )

    def close(self):
        self._client.close()
