"""Opt-in bfloat16 wire compression for the PS-mode hot path.

The dominant PS-mode wire cost is the dense model pull every
``get_model_steps`` and the per-step gradient push (reference
worker.py:748-825 + report_gradient — the reference ships both as f32
protobufs with no compression). Training math tolerates bf16 transport:
the receiver upcasts back to f32 before any optimizer/apply step, so
only the wire narrows — params/grads lose the low 16 mantissa bits in
transit, the standard TPU-ecosystem gradient-compression tradeoff.

Protocol: the sender downcasts float32 tensor payloads (dense values and
sparse row values alike) and lists the affected tensor names in a
``compressed_f32`` message field; the receiver upcasts exactly those
names. Tensors that are natively bf16 (or any other dtype) pass through
untouched in both directions, so a bf16-parameter model composes with
compression, and a sender/receiver flag mismatch degrades to "no
compression" rather than corruption (the frames are self-describing).

Copy discipline (docs/wire.md, edlint R10): ``compress_tensors`` only
MARKS tensors (``Tensor.wire_dtype``) — the actual f32 -> bf16 narrowing
is fused into the codec's single frame copy-out
(common/tensor.write_tensor_frame), so compression no longer pays its
own ``astype`` allocation pass; the wire bytes are identical to the
eager-downcast protocol. On the in-process transport (no serialization)
a marked tensor passes through at full f32 precision — strictly less
rounding than the wire pays, same contract for the receiver. The
receiver-side upcast is the decode path's one required materialization
for compressed tensors (R10-ratcheted with that reason).

Enable with ``--wire_dtype=bfloat16`` (relayed master -> worker/PS pods
via the argv relay, so one flag configures the whole job).
"""

import numpy as np

from elasticdl_tpu.common.dtypes import dtype_name_to_numpy
from elasticdl_tpu.common.tensor import Tensor


def compress_tensors(tensors, wire_dtype):
    """Mark f32 payloads to ride the wire as ``wire_dtype``; returns
    ``(tensors, compressed_names)``. No-op when ``wire_dtype`` is falsy.

    Marking is allocation-free: the returned tensors alias the input
    arrays, and the downcast happens inside the frame writer's single
    memcpy."""
    if not wire_dtype:
        return list(tensors), []
    if wire_dtype != "bfloat16":
        raise ValueError("unsupported wire_dtype %r" % (wire_dtype,))
    # resolved lazily: common/dtypes omits bfloat16 when ml_dtypes is
    # absent, and that environment must still serve uncompressed RPCs
    bf16 = dtype_name_to_numpy("bfloat16")
    out, names = [], []
    for t in tensors:
        if t.values is not None and t.values.dtype == np.float32:
            marked = Tensor(t.name, t.values, t.indices)
            marked.wire_dtype = bf16
            out.append(marked)
            names.append(t.name)
        else:
            out.append(t)
    return out, names


def decompress_tensors(tensors, compressed_names):
    """Upcast the named tensors' payloads back to f32.

    Payloads that arrive already f32 (the in-process transport, where a
    compression mark never materialized) pass through without a copy —
    only the mark is shed, so a later re-serialize cannot silently
    downcast them again."""
    if not compressed_names:
        return list(tensors)
    names = set(compressed_names)
    out = []
    for t in tensors:
        if t.name not in names or t.values is None:
            out.append(t)
        elif t.values.dtype == np.float32:
            out.append(Tensor(t.name, t.values, t.indices))
        else:
            # the one required decode materialization: an f32 consumer
            # cannot read bf16 in place (edlint R10 ratchet)
            out.append(
                Tensor(t.name, t.values.astype(np.float32), t.indices)
            )
    return out
