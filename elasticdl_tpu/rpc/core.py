"""Control-plane RPC: gRPC transport without protoc codegen.

Parity: the reference's wire layer is gRPC + generated protobuf stubs
(elasticdl/proto, Makefile codegen). Here the transport is still gRPC
(C-core — the native substrate the reference relies on, SURVEY.md §2.4)
but messages are self-describing frames from the framework codec
(common/tensor.py), served through generic bytes-in/bytes-out handlers —
no .proto build step, same 256 MB caps. Only control-plane and host-PS
traffic rides this; the ALLREDUCE tensor plane never leaves device HBM.

Message model: a dict whose values are JSON scalars/lists, np.ndarrays,
``Tensor`` objects, or lists of Tensors.
"""

import json
import struct
import time
from concurrent import futures

import numpy as np

from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.common.tensor import (
    Tensor,
    WireArena,
    deserialize_tensor,
    is_device_array,
    plan_tensor_frame,
    write_tensor_frame,
)
from elasticdl_tpu.utils import profiling

_SERVICE = "elasticdl_tpu.Rpc"

# Client-side telemetry, one family each shared by every Client in the
# process (docs/observability.md). The registry's get-or-create is
# idempotent and thread-safe, so each Client just asks for the
# families at init — nothing happens at import time.
def _client_metrics():
    return (
        profiling.metrics.histogram(
            "edl_rpc_client_latency_seconds",
            "Client-observed RPC latency by method "
            "(per attempt, successes only)",
            labels=("method",),
        ),
        profiling.metrics.counter(
            "edl_rpc_client_errors_total",
            "Client-observed RPC failures by method and "
            "gRPC status code (per attempt)",
            labels=("method", "code"),
        ),
        profiling.metrics.counter(
            "edl_rpc_client_retries_total",
            "UNAVAILABLE retries by method",
            labels=("method",),
        ),
    )


class MessagePlan:
    """Exact layout of one packed message (docs/wire.md).

    ``segments`` holds ``("frame", tensor_frame_plan)`` or
    ``("raw", bytes_like)`` entries with their byte lengths already
    known, so any writer (the bytearray packer below, the shm slot
    packer) allocates once and performs ONE memcpy per payload."""

    __slots__ = ("header", "segments", "total")

    def __init__(self, header, segments, total):
        self.header = header
        self.segments = segments
        self.total = total


def plan_message(msg):
    """dict -> :class:`MessagePlan`. Arrays/Tensors ride as codec
    frames; the plan computes every offset up front (scatter-gather)."""
    header = {}
    segments = []

    def add_frame(t):
        plan = plan_tensor_frame(t)
        segments.append(("frame", plan, plan[4]))
        return len(segments) - 1

    for key, value in msg.items():
        if key == "_wire_arena":
            continue  # decode-side lifetime handle, never a wire field
        if isinstance(value, Tensor):
            header[key] = {"t": "tensor", "i": add_frame(value)}
        elif isinstance(value, np.ndarray) or is_device_array(value):
            # jax.Array payloads frame directly: the plan reads aval
            # metadata only, the packer's frame write is the single
            # host copy (dlpack bridge, docs/wire.md) — no np.asarray
            # staging ever happens on this path
            header[key] = {"t": "array", "i": add_frame(Tensor(key, value))}
        elif (
            isinstance(value, (list, tuple))
            and value
            and isinstance(value[0], Tensor)
        ):
            header[key] = {"t": "tensors", "i": [add_frame(t) for t in value]}
        elif isinstance(value, (bytes, bytearray, memoryview)):
            if isinstance(value, memoryview) and (
                value.itemsize != 1 or value.ndim != 1
            ):
                # len() counts ELEMENTS; the frame needs bytes (a
                # non-contiguous view raises here — loudly, not as a
                # torn length prefix)
                value = value.cast("B")
            segments.append(("raw", value, len(value)))
            header[key] = {"t": "bytes", "i": len(segments) - 1}
        else:
            header[key] = {"t": "json", "v": value}
    hdr = json.dumps(header).encode("utf-8")
    total = 8 + len(hdr) + sum(8 + n for _, _, n in segments)
    return MessagePlan(hdr, segments, total)


def pack_message_into(plan, buf, off=0):
    """Write a planned message into ``buf`` (writable memoryview /
    bytearray) at ``off``; returns the offset past the message."""
    if not isinstance(buf, memoryview):
        buf = memoryview(buf)  # bytearray slices copy; views don't
    hdr = plan.header
    struct.pack_into("<I", buf, off, len(hdr))
    off += 4
    buf[off : off + len(hdr)] = hdr
    off += len(hdr)
    struct.pack_into("<I", buf, off, len(plan.segments))
    off += 4
    for kind, payload, nbytes in plan.segments:
        struct.pack_into("<Q", buf, off, nbytes)
        off += 8
        if kind == "frame":
            off = write_tensor_frame(payload, buf, off)
        else:
            buf[off : off + nbytes] = payload
            off += nbytes
    return off


def pack_message(msg):
    """dict -> one exactly-sized frame (``bytearray``, bytes-like).

    One preallocation, one memcpy per payload, zero intermediate
    per-segment ``bytes`` objects — the seed codec's per-frame joins,
    ``serialize_tensors``' double join, and this function's own outer
    join all folded into the single scatter-gather write. Byte-layout
    identical to the historical packer."""
    plan = plan_message(msg)
    buf = bytearray(plan.total)
    pack_message_into(plan, buf)
    return buf


def unpack_message(data, arena=None, writable=False):
    """bytes-like -> dict, zero-copy: segments stay memoryview slices
    of ``data`` and the field decoders decide what materializes —
    tensor/array fields decode to READ-ONLY views pinned to the buffer,
    ``bytes`` fields materialize (callers expect hashable bytes; tensor
    payloads never ride that kind), json fields are scalars. ``arena``
    (a :class:`WireArena`) rides along under ``"_wire_arena"`` so the
    consumer controls the buffer's lifetime (mandatory for shm slots;
    see common/tensor.release_message).

    ``writable=True`` keeps tensor views writable when ``data`` is a
    writable buffer — the device-resident PS shard's opt-in
    (rpc/shm_transport.install_shm_endpoint) so request payloads can
    dlpack-import straight to device (common/tensor.
    device_from_host_view); numpy cannot export read-only buffers."""
    view = data if isinstance(data, memoryview) else memoryview(data)
    if not view.readonly and not writable:
        view = view.toreadonly()
    (hlen,) = struct.unpack_from("<I", view, 0)
    header = json.loads(bytes(view[4 : 4 + hlen]))
    off = 4 + hlen
    (nseg,) = struct.unpack_from("<I", view, off)
    off += 4
    segments = []
    for _ in range(nseg):
        (slen,) = struct.unpack_from("<Q", view, off)
        off += 8
        segments.append(view[off : off + slen])
        off += slen
    msg = {}
    for key, spec in header.items():
        kind = spec["t"]
        if kind == "json":
            msg[key] = spec["v"]
        elif kind == "bytes":
            msg[key] = bytes(segments[spec["i"]])
        elif kind == "tensor":
            msg[key] = deserialize_tensor(segments[spec["i"]], writable)
        elif kind == "array":
            msg[key] = deserialize_tensor(segments[spec["i"]], writable).values
        elif kind == "tensors":
            msg[key] = [
                deserialize_tensor(segments[i], writable)
                for i in spec["i"]
            ]
        else:
            raise ValueError("unknown field kind %r" % kind)
    if arena is not None:
        msg["_wire_arena"] = arena
    return msg


class _GenericHandler:
    def __init__(self, methods):
        import grpc

        self._grpc = grpc
        self._methods = methods

    def service(self, handler_call_details):
        name = handler_call_details.method.rsplit("/", 1)[-1]
        fn = self._methods.get(name)
        if fn is None:
            return None

        def handler(request_bytes, context):
            reply = fn(unpack_message(request_bytes))
            # cygrpc's SendMessageOperation is typed `bytes` exactly
            # (grpc 1.68): this conversion is the single transport
            # handoff copy on the reply direction — the shm transport's
            # slot replies skip it (edlint R10 ratchet)
            return bytes(pack_message(reply if reply is not None else {}))

        return self._grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


def serve(methods, port, max_workers=64):
    """Start a gRPC server exposing ``methods`` {name: fn(dict)->dict}.

    Returns the started server (64 threads like the reference PS,
    ps/parameter_server.py:33-56).
    """
    import grpc

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
            (
                "grpc.max_receive_message_length",
                GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
            ),
        ],
        handlers=(_GenericHandler(methods),),
    )
    chosen = server.add_insecure_port("[::]:%d" % port)
    if chosen == 0:
        raise RuntimeError("failed to bind RPC server port %d" % port)
    server.start()
    server._edl_port = chosen
    return server


class Client:
    """Bytes-frame RPC client: ``client.call("method", **fields)``.

    ``deadline_s``: per-attempt gRPC deadline in seconds; ``None``
    (default) keeps the historical block-forever behavior — the
    control-plane master channel relies on it (a worker parked on
    ``get_task`` against a busy master must wait, not error). The PS
    data plane passes a finite deadline so a dead PS pod fails the call
    in seconds and feeds the worker's existing minibatch retry loop
    instead of hanging a fan-out forever.

    ``retries``/``backoff_s``: transient-transport retry. Only
    UNAVAILABLE is retried (channel down / connection refused — the
    shape a restarting PS pod presents); DEADLINE_EXCEEDED is NOT,
    so the caller-visible failure bound stays ~``deadline_s`` rather
    than ``deadline_s * (retries + 1)``. Backoff doubles per attempt.
    """

    def __init__(self, addr, deadline_s=None, retries=0, backoff_s=0.2):
        import grpc

        self._grpc = grpc
        self._deadline_s = deadline_s if deadline_s else None
        self._retries = retries
        self._backoff_s = backoff_s
        self._latency, self._errors, self._retried = _client_metrics()
        self._sleep = time.sleep  # injectable for tests
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                (
                    "grpc.max_send_message_length",
                    GRPC.MAX_SEND_MESSAGE_LENGTH,
                ),
                (
                    "grpc.max_receive_message_length",
                    GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
                ),
            ],
        )
        self._stubs = {}

    def call(self, rpc_name, _retriable=True, _plan=None, **fields):
        """``_retriable=False`` opts this call out of the UNAVAILABLE
        retry: a non-idempotent RPC (``push_gradient`` — async mode
        applies on receipt) must not be resent when the connection died
        AFTER the server processed it, or the gradient applies twice.
        The underscore keeps the name out of the protocol field space.

        ``_plan``: an already-built :class:`MessagePlan` for ``fields``
        (the shm transport's per-call fallback hands its plan over so
        an oversized payload is not planned twice).
        """
        stub = self._stubs.get(rpc_name)
        if stub is None:
            stub = self._channel.unary_unary(
                "/%s/%s" % (_SERVICE, rpc_name),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            # one Client is shared by every fan-out leg of a
            # multi-table pull: setdefault keeps the cache coherent
            # when two legs race the first call of a method (the loser
            # stub is garbage, never a torn entry)
            stub = self._stubs.setdefault(rpc_name, stub)
        if _plan is None and "_sctx" not in fields and (
            "_shm_req" not in fields
        ):
            # cross-process tracing (docs/observability.md): the
            # innermost open span's [trace_id, span_id] rides as one
            # small json field, so the serving process's rpc span joins
            # the caller's trace. An already-built plan (the shm
            # transport's oversize fallback) carries its own context,
            # and a slot-riding call (_shm_req) already injected into
            # the slot payload — the control message needs no copy.
            sctx = profiling.wire_span_context()
            if sctx is not None:
                fields["_sctx"] = sctx
        plan = _plan if _plan is not None else plan_message(fields)
        buf = bytearray(plan.total)
        pack_message_into(plan, buf)
        # cygrpc requires an exact `bytes` request: the one transport
        # handoff copy of the send direction (edlint R10 ratchet); the
        # scatter-gather packer already collapsed everything upstream
        # of it to one memcpy per payload
        request = bytes(buf)
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                reply = stub(request, timeout=self._deadline_s)
                # latency covers the wire + service time of a SUCCESSFUL
                # attempt; failures count in the errors family instead
                # (mixing error turnaround into the latency histogram
                # would poison the tail percentiles a fleet dashboard
                # alerts on)
                self._latency.observe(
                    time.perf_counter() - t0, method=rpc_name
                )
                # the gRPC reply bytes become the arena: decoded tensor
                # views pin them by refcount, and release_message() is
                # the uniform consumer-side hook shared with the shm
                # path (where release actually recycles a slot)
                return unpack_message(reply, arena=WireArena(reply))
            except self._grpc.RpcError as err:
                code = err.code() if callable(getattr(err, "code", None)) else None
                self._errors.inc(
                    method=rpc_name,
                    code=code.name if code is not None else "UNKNOWN",
                )
                retriable = (
                    _retriable
                    and code == self._grpc.StatusCode.UNAVAILABLE
                    and attempt < self._retries
                )
                if not retriable:
                    raise
                self._retried.inc(method=rpc_name)
                self._sleep(self._backoff_s * (2 ** attempt))
                attempt += 1

    def close(self):
        self._channel.close()
