"""Control-plane RPC: gRPC transport without protoc codegen.

Parity: the reference's wire layer is gRPC + generated protobuf stubs
(elasticdl/proto, Makefile codegen). Here the transport is still gRPC
(C-core — the native substrate the reference relies on, SURVEY.md §2.4)
but messages are self-describing frames from the framework codec
(common/tensor.py), served through generic bytes-in/bytes-out handlers —
no .proto build step, same 256 MB caps. Only control-plane and host-PS
traffic rides this; the ALLREDUCE tensor plane never leaves device HBM.

Message model: a dict whose values are JSON scalars/lists, np.ndarrays,
``Tensor`` objects, or lists of Tensors.
"""

import json
import struct
import time
from concurrent import futures

import numpy as np

from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.common.tensor import (
    Tensor,
    deserialize_tensor,
    serialize_tensor,
)
from elasticdl_tpu.utils import profiling

_SERVICE = "elasticdl_tpu.Rpc"

# Client-side telemetry, one family each shared by every Client in the
# process (docs/observability.md). The registry's get-or-create is
# idempotent and thread-safe, so each Client just asks for the
# families at init — nothing happens at import time.
def _client_metrics():
    return (
        profiling.metrics.histogram(
            "edl_rpc_client_latency_seconds",
            "Client-observed RPC latency by method "
            "(per attempt, successes only)",
            labels=("method",),
        ),
        profiling.metrics.counter(
            "edl_rpc_client_errors_total",
            "Client-observed RPC failures by method and "
            "gRPC status code (per attempt)",
            labels=("method", "code"),
        ),
        profiling.metrics.counter(
            "edl_rpc_client_retries_total",
            "UNAVAILABLE retries by method",
            labels=("method",),
        ),
    )


def pack_message(msg):
    """dict -> bytes. Arrays/Tensors ride as codec frames."""
    header = {}
    segments = []

    def add_segment(data):
        segments.append(data)
        return len(segments) - 1

    for key, value in msg.items():
        if isinstance(value, Tensor):
            header[key] = {"t": "tensor", "i": add_segment(value.to_bytes())}
        elif isinstance(value, np.ndarray):
            header[key] = {
                "t": "array",
                "i": add_segment(serialize_tensor(Tensor(key, value))),
            }
        elif (
            isinstance(value, (list, tuple))
            and value
            and isinstance(value[0], Tensor)
        ):
            idxs = [add_segment(t.to_bytes()) for t in value]
            header[key] = {"t": "tensors", "i": idxs}
        elif isinstance(value, (bytes, bytearray)):
            header[key] = {"t": "bytes", "i": add_segment(bytes(value))}
        else:
            header[key] = {"t": "json", "v": value}
    hdr = json.dumps(header).encode("utf-8")
    out = [struct.pack("<I", len(hdr)), hdr, struct.pack("<I", len(segments))]
    for seg in segments:
        out.append(struct.pack("<Q", len(seg)))
        out.append(seg)
    return b"".join(out)


def unpack_message(data):
    view = memoryview(data)
    (hlen,) = struct.unpack_from("<I", view, 0)
    header = json.loads(bytes(view[4 : 4 + hlen]).decode("utf-8"))
    off = 4 + hlen
    (nseg,) = struct.unpack_from("<I", view, off)
    off += 4
    segments = []
    for _ in range(nseg):
        (slen,) = struct.unpack_from("<Q", view, off)
        off += 8
        segments.append(bytes(view[off : off + slen]))
        off += slen
    msg = {}
    for key, spec in header.items():
        kind = spec["t"]
        if kind == "json":
            msg[key] = spec["v"]
        elif kind == "bytes":
            msg[key] = segments[spec["i"]]
        elif kind == "tensor":
            msg[key] = deserialize_tensor(segments[spec["i"]])
        elif kind == "array":
            msg[key] = deserialize_tensor(segments[spec["i"]]).values
        elif kind == "tensors":
            msg[key] = [deserialize_tensor(segments[i]) for i in spec["i"]]
        else:
            raise ValueError("unknown field kind %r" % kind)
    return msg


class _GenericHandler:
    def __init__(self, methods):
        import grpc

        self._grpc = grpc
        self._methods = methods

    def service(self, handler_call_details):
        name = handler_call_details.method.rsplit("/", 1)[-1]
        fn = self._methods.get(name)
        if fn is None:
            return None

        def handler(request_bytes, context):
            reply = fn(unpack_message(request_bytes))
            return pack_message(reply if reply is not None else {})

        return self._grpc.unary_unary_rpc_method_handler(
            handler,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )


def serve(methods, port, max_workers=64):
    """Start a gRPC server exposing ``methods`` {name: fn(dict)->dict}.

    Returns the started server (64 threads like the reference PS,
    ps/parameter_server.py:33-56).
    """
    import grpc

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
            (
                "grpc.max_receive_message_length",
                GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
            ),
        ],
        handlers=(_GenericHandler(methods),),
    )
    chosen = server.add_insecure_port("[::]:%d" % port)
    if chosen == 0:
        raise RuntimeError("failed to bind RPC server port %d" % port)
    server.start()
    server._edl_port = chosen
    return server


class Client:
    """Bytes-frame RPC client: ``client.call("method", **fields)``.

    ``deadline_s``: per-attempt gRPC deadline in seconds; ``None``
    (default) keeps the historical block-forever behavior — the
    control-plane master channel relies on it (a worker parked on
    ``get_task`` against a busy master must wait, not error). The PS
    data plane passes a finite deadline so a dead PS pod fails the call
    in seconds and feeds the worker's existing minibatch retry loop
    instead of hanging a fan-out forever.

    ``retries``/``backoff_s``: transient-transport retry. Only
    UNAVAILABLE is retried (channel down / connection refused — the
    shape a restarting PS pod presents); DEADLINE_EXCEEDED is NOT,
    so the caller-visible failure bound stays ~``deadline_s`` rather
    than ``deadline_s * (retries + 1)``. Backoff doubles per attempt.
    """

    def __init__(self, addr, deadline_s=None, retries=0, backoff_s=0.2):
        import grpc

        self._grpc = grpc
        self._deadline_s = deadline_s if deadline_s else None
        self._retries = retries
        self._backoff_s = backoff_s
        self._latency, self._errors, self._retried = _client_metrics()
        self._sleep = time.sleep  # injectable for tests
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                (
                    "grpc.max_send_message_length",
                    GRPC.MAX_SEND_MESSAGE_LENGTH,
                ),
                (
                    "grpc.max_receive_message_length",
                    GRPC.MAX_RECEIVE_MESSAGE_LENGTH,
                ),
            ],
        )
        self._stubs = {}

    def call(self, rpc_name, _retriable=True, **fields):
        """``_retriable=False`` opts this call out of the UNAVAILABLE
        retry: a non-idempotent RPC (``push_gradient`` — async mode
        applies on receipt) must not be resent when the connection died
        AFTER the server processed it, or the gradient applies twice.
        The underscore keeps the name out of the protocol field space.
        """
        stub = self._stubs.get(rpc_name)
        if stub is None:
            stub = self._channel.unary_unary(
                "/%s/%s" % (_SERVICE, rpc_name),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            # one Client is shared by every fan-out leg of a
            # multi-table pull: setdefault keeps the cache coherent
            # when two legs race the first call of a method (the loser
            # stub is garbage, never a torn entry)
            stub = self._stubs.setdefault(rpc_name, stub)
        request = pack_message(fields)
        attempt = 0
        while True:
            t0 = time.perf_counter()
            try:
                reply = stub(request, timeout=self._deadline_s)
                # latency covers the wire + service time of a SUCCESSFUL
                # attempt; failures count in the errors family instead
                # (mixing error turnaround into the latency histogram
                # would poison the tail percentiles a fleet dashboard
                # alerts on)
                self._latency.observe(
                    time.perf_counter() - t0, method=rpc_name
                )
                return unpack_message(reply)
            except self._grpc.RpcError as err:
                code = err.code() if callable(getattr(err, "code", None)) else None
                self._errors.inc(
                    method=rpc_name,
                    code=code.name if code is not None else "UNKNOWN",
                )
                retriable = (
                    _retriable
                    and code == self._grpc.StatusCode.UNAVAILABLE
                    and attempt < self._retries
                )
                if not retriable:
                    raise
                self._retried.inc(method=rpc_name)
                self._sleep(self._backoff_s * (2 ** attempt))
                attempt += 1

    def close(self):
        self._channel.close()
