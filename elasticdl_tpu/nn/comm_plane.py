"""One comm-plane interface over the two embedding planes.

Production recsys at the ROADMAP's millions-of-users scale runs BOTH
sparse storage planes in the same job: dense layers (and HBM-resident
tables) synced on-device, while the multi-hundred-GB tables stay
sharded on the host-PS fleet ("Elastic Model Aggregation with Parameter
Service", PAPERS.md 2204.03211). Historically ``nn/embedding.py`` (the
host-PS plane) and ``nn/hbm_embedding.py`` (the in-mesh a2a plane) were
separate per-zoo code paths selected wholesale; this module gives them
ONE interface so a single model mixes planes per table
(docs/embedding_planes.md):

    plan_lookup -> pull -> scatter -> push

- ``plan_lookup_multi`` is the PR-1 dedup planner, now canonical here:
  the host-side batch-wide unique plan for the PS plane, and the
  declared twin of the in-graph :func:`~elasticdl_tpu.nn.sparse_comms.
  padded_unique` plan the HBM plane runs under jit.
- ``pull`` fetches the planned unique rows (a no-op for in-graph
  planes, whose "pull" is the a2a collective inside the jitted step).
- ``scatter`` pads pulled rows to the plan's static bucket so the
  jitted step's shapes stay stable across batches.
- ``push`` ships the combined per-unique-row gradients back; for the
  PS plane it rides the PR-2 non-blocking push window, whose
  :meth:`~CommPlane.drain` the worker calls at every SSP boundary in
  BOTH trainer modes (task/eval/checkpoint), so the staleness bound is
  plane-shared.

The PR-1 :class:`HotRowCache` also lives here now — one version-tagged
cache instance can back the PS plane's pulls and (ROADMAP item 3) a
serving plane's read-through lookups, whatever plane a table rides.

Per-table selection (``plane=``): :func:`make_embedding` builds the
layer for one table from its plane name, and
:func:`resolve_table_planes` parses the zoo-facing
``embedding_plane=ps|hbm|hybrid|"table:plane/table:plane"`` spec.

The hybrid trainer mode itself lives in worker/worker.py
(``embedding_plane="hybrid"``): dense params and HBM tables stay in the
local/allreduce world (no PS round trip for dense), PS-resident tables
are served by :class:`EmbeddingPullPipeline` — the pull for batch N+1
fans out on a background thread while batch N's jitted step runs.
"""

import threading
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

PLANES = ("ps", "hbm")


# ---------------------------------------------------------------------------
# the dedup planner (PR-1, canonical home; nn/embedding.py re-exports)
# ---------------------------------------------------------------------------


def plan_lookup(ids, bucket_min=8):
    """unique ids + per-element positions, padded to a pow2 bucket.

    Returns (unique_ids (k,), idx ids.shape int32, bucket_size).
    Static bucket sizes keep the jitted step's shapes stable across
    batches with different unique-id counts.
    """
    unique, (idx,), bucket = plan_lookup_multi([ids], bucket_min)
    return unique, idx, bucket


def plan_lookup_multi(ids_list, bucket_min=8, dedup=True):
    """Union lookup plan over every call of one layer per forward.

    Returns (unique_ids (k,), [idx per call], bucket_size): one shared
    rows pull covers all calls (a tied embedding reads the same table),
    each call keeping its own position array into that buffer.

    This host-side batch-wide dedup is the PS plane's half of the
    sparse-comms fast path (nn/sparse_comms.py): only unique rows are
    pulled, and since every occurrence gathers from its unique slot, the
    step's row gradients come back ALREADY combined (the take VJP
    scatter-adds over the plan's positions) — one row per unique id in
    both wire directions. ``dedup=False`` builds the naive
    per-occurrence plan (every id keeps its own slot; duplicates pull
    and push duplicate rows) — the pre-fast-path wire behavior, kept
    for benchmarking and equivalence tests.
    """
    arrays = [np.asarray(ids) for ids in ids_list]
    flat = np.concatenate(
        [a.reshape(-1).astype(np.int64) for a in arrays]
    )
    if dedup:
        unique, inverse = np.unique(flat, return_inverse=True)
    else:
        unique = flat
        inverse = np.arange(flat.size, dtype=np.int64)
    k = len(unique)
    bucket = bucket_min
    while bucket < k:
        bucket *= 2
    idxs, off = [], 0
    for a in arrays:
        n = a.size
        idxs.append(
            inverse[off : off + n].reshape(a.shape).astype(np.int32)
        )
        off += n
    return unique, idxs, bucket


def pad_rows_to_bucket(rows, bucket):
    """Pad pulled (k, dim) rows with zeros to the plan's static bucket.

    The shared ``scatter`` step of the host-side planes: the jitted
    step gathers from a pow2-sized buffer, so its compiled shapes are
    stable across batches with different unique-id counts."""
    rows = np.asarray(rows, dtype=np.float32)
    if rows.shape[0] >= bucket:
        return rows
    return np.concatenate(
        [
            rows,
            np.zeros((bucket - rows.shape[0], rows.shape[1]), np.float32),
        ]
    )


# ---------------------------------------------------------------------------
# the version-tagged hot-row cache (PR-1, canonical home;
# worker/ps_client.py re-exports)
# ---------------------------------------------------------------------------


class HotRowCache:
    """Worker-side LRU of recently pulled embedding rows, with
    version-tagged invalidation.

    Power-law id distributions re-pull the same head rows every batch;
    this cache serves those repeats locally instead of over gRPC. Every
    entry is tagged with the owning PS shard's model version at pull
    time; the client notes the newest version it has SEEN per shard
    (from pull AND push responses — the same version counter
    ps/servicer.py's staleness machinery modulates the LR by), and an
    entry older than ``window`` versions behind that is a miss. The
    served rows are therefore stale by at most ``window`` optimizer
    steps of that shard — the same bounded-staleness contract SSP local
    updates already run under (``get_model_steps``, with the async LR
    discounted by 1/staleness via master/learning_rate_modulator.py) —
    so the cache never adds a staleness mode the training loop doesn't
    already tolerate.

    Plane-shared since the comm-plane refactor: the cache is keyed by
    (table, id) with the plane-neutral shard/version tag, so one
    instance can back every PS-resident table of a hybrid model and,
    later, a serving worker's read-through lookups (ROADMAP item 3).

    Thread-safe: with the overlapped data plane, push completions note
    versions from the fan-out/push threads while the worker thread
    probes and fills, so every mutation runs under one internal lock.
    """

    def __init__(self, max_rows, window=1):
        if max_rows <= 0:
            raise ValueError("max_rows must be positive")
        if window < 0:
            raise ValueError("window must be >= 0")
        self._max_rows = max_rows
        self._window = window
        self._mu = threading.Lock()
        self._rows = OrderedDict()  # (name, id) -> (shard, version, row)
        self._latest = {}  # shard -> newest version seen in any response
        self.hits = 0
        self.misses = 0
        # per-table [hits, misses, evictions] — the tiered store's
        # admission-signal series, exported as labeled edl_cache_*
        # counters (worker telemetry + scorer); the aggregate
        # hits/misses attributes above stay for existing readers
        self._table_stats = {}

    def note_version(self, shard, version):
        """Record a version observed in shard ``shard``'s response."""
        if version is None or version < 0:
            return
        with self._mu:
            if version > self._latest.get(shard, -1):
                self._latest[shard] = version

    def invalidate_shard(self, shard, version=None):
        """Drop every entry tagged with ``shard`` and re-anchor its
        version clock — the reconnect protocol's cache half
        (docs/ps_recovery.md).

        A relaunched shard restores an OLDER snapshot and mints a new
        epoch: rows cached under the dead incarnation's tags are no
        longer the shard's truth (the shard re-applies the rolled-back
        window differently), and the max-only ``note_version`` clock
        would otherwise hold the dead incarnation's high-water mark —
        every freshly pulled row would tag below ``latest - window``
        and miss forever (a permanent miss storm). ``version`` (the
        restored shard's current version) re-anchors the clock;
        ``None`` just forgets the shard. Returns the entry count
        dropped."""
        with self._mu:
            victims = [
                key
                for key, (entry_shard, _, _) in self._rows.items()
                if entry_shard == shard
            ]
            for key in victims:
                del self._rows[key]
            if version is not None and version >= 0:
                self._latest[shard] = version
            else:
                self._latest.pop(shard, None)
            return len(victims)

    def invalidate_table(self, name, below_version=None):
        """Drop ``name``'s entries tagged below ``below_version``
        (every entry when None), touching NOTHING else — no other
        table's rows, no shard version clock.

        The delta-sync fallback (docs/serving.md): when a table's
        delta answer is incomplete (the PS pruned past the scorer's
        sync point), only THAT table's potentially-moved rows may be
        dropped — ``invalidate_shard`` would evict every co-sharded
        table's hot rows and re-anchor the clock for a failure mode
        that is not a relaunch. ``below_version`` compares against
        entry tags from whichever shard wrote them: version clocks are
        per-shard, so cross-shard comparison can only over-drop (a
        cache miss), never under-drop. Returns the entry count
        dropped."""
        with self._mu:
            victims = [
                key
                for key, (_, version, _) in self._rows.items()
                if key[0] == name
                and (below_version is None or version < below_version)
            ]
            for key in victims:
                del self._rows[key]
            return len(victims)

    def refresh_table(self, name, shard, version, changed_ids, since):
        """Apply one table's delta from ``shard``: entries whose id is
        in ``changed_ids`` (or whose tag predates ``since``, the
        delta's lower bound — the log knows nothing about them) drop;
        every other entry of (``name``, ``shard``) is provably
        unchanged through ``version`` and is re-tagged fresh. Also
        advances the shard's version clock. Returns
        ``(dropped_ids, retagged_count)`` — the dropped ids let the
        delta sync re-pull exactly the hot rows that moved
        (docs/serving.md)."""
        changed = {int(i) for i in changed_ids}
        dropped, retagged = [], 0
        with self._mu:
            for key in list(self._rows):
                entry_shard, entry_version, row = self._rows[key]
                if key[0] != name or entry_shard != shard:
                    continue
                if key[1] in changed or entry_version < since:
                    del self._rows[key]
                    dropped.append(key[1])
                else:
                    self._rows[key] = (shard, version, row)
                    retagged += 1
            if version > self._latest.get(shard, -1):
                self._latest[shard] = version
        return dropped, retagged

    def max_live_lag(self):
        """Worst-case staleness (in shard versions) any cache HIT could
        currently serve: the max over entries of
        ``latest_seen(shard) - entry_version``, counting only entries
        inside the window (anything beyond it would miss and drop at
        probe time, so it cannot be served). This is the serving
        plane's ``edl_scorer_row_staleness_versions`` gauge — by
        construction it never exceeds the configured window
        (docs/serving.md freshness contract)."""
        with self._mu:
            worst = 0
            for (_, _), (shard, version, _) in self._rows.items():
                lag = self._latest.get(shard, -1) - version
                if 0 < lag <= self._window and lag > worst:
                    worst = lag
            return worst

    def get(self, name, row_id):
        """The cached row, or None on miss/stale (stale entries drop)."""
        with self._mu:
            return self._get_locked(name, row_id)

    def get_rows(self, name, row_ids):
        """Probe one batch under a single lock acquisition; one entry
        per id, None on miss (the read-side twin of put_rows)."""
        with self._mu:
            return [self._get_locked(name, r) for r in row_ids]

    def _table_stat_locked(self, name):
        stat = self._table_stats.get(name)
        if stat is None:
            stat = self._table_stats[name] = [0, 0, 0]
        return stat

    def _get_locked(self, name, row_id):
        key = (name, int(row_id))
        entry = self._rows.get(key)
        if entry is None:
            self.misses += 1
            self._table_stat_locked(name)[1] += 1
            return None
        shard, version, row = entry
        if version < self._latest.get(shard, -1) - self._window:
            del self._rows[key]
            self.misses += 1
            self._table_stat_locked(name)[1] += 1
            return None
        self._rows.move_to_end(key)
        self.hits += 1
        self._table_stat_locked(name)[0] += 1
        return row

    def put(self, name, row_id, shard, version, row):
        if version is None:
            return  # unversioned response: nothing safe to tag with
        with self._mu:
            self._put_locked(name, row_id, shard, version, row)

    def put_rows(self, name, row_ids, shard, version, rows):
        """Insert one pulled batch under a single lock acquisition."""
        if version is None:
            return
        with self._mu:
            for row_id, row in zip(row_ids, rows):
                self._put_locked(name, row_id, shard, version, row)

    def _put_locked(self, name, row_id, shard, version, row):
        key = (name, int(row_id))
        # copy: ``row`` is usually a view into the pull's full response
        # array, and storing the view would pin that whole buffer for
        # as long as any one of its rows stays hot
        self._rows[key] = (shard, version, np.array(row, np.float32))
        self._rows.move_to_end(key)
        while len(self._rows) > self._max_rows:
            victim_key, _ = self._rows.popitem(last=False)
            # capacity eviction, charged to the VICTIM's table — the
            # signal that says which table's working set is being
            # squeezed out of the top tier
            self._table_stat_locked(victim_key[0])[2] += 1

    def table_stats(self):
        """``{table: {"hits": n, "misses": n, "evictions": n}}`` — a
        consistent copy of the per-table counters (the tiered store's
        admission-policy input, exported as ``edl_cache_*{table=}``)."""
        with self._mu:
            return {
                name: {
                    "hits": stat[0],
                    "misses": stat[1],
                    "evictions": stat[2],
                }
                for name, stat in self._table_stats.items()
            }

    def __len__(self):
        with self._mu:
            return len(self._rows)


# ---------------------------------------------------------------------------
# the plane interface
# ---------------------------------------------------------------------------


class CommPlane:
    """Abstract comm plane for one (or more) embedding tables.

    ``in_graph`` planes perform their lookup INSIDE the jitted step
    (the HBM a2a plane); host planes pull rows over a data-plane
    channel before the step and push combined row gradients after it.
    """

    name = "abstract"
    in_graph = False

    def plan_lookup_multi(self, ids_list, bucket_min=8, dedup=True):
        """The shared dedup planner (see module-level twin)."""
        return plan_lookup_multi(ids_list, bucket_min=bucket_min, dedup=dedup)

    def pull(self, ids_by_table):
        """{table_name: unique_ids} -> {table_name: rows} in ONE
        logical round (implementations fan shard legs out)."""
        raise NotImplementedError

    def scatter(self, rows, bucket):
        """Pulled rows -> the static-shape buffer the step gathers from."""
        return pad_rows_to_bucket(rows, bucket)

    def push(self, sparse_tensors, version):
        """Ship combined row gradients; returns (accepted, version)."""
        raise NotImplementedError

    def drain(self):
        """Settle any in-flight async pushes (SSP-boundary hook).

        Returns (accepted, version) like the PS push window; planes
        with no window return (True, -1)."""
        return True, -1

    @property
    def cache(self):
        """The shared :class:`HotRowCache`, or None."""
        return None

    def close(self):
        """Release plane resources (threads, channels)."""


class PsPlane(CommPlane):
    """The sharded host-PS plane over a ``worker.ps_client.PSClient``.

    pull rides the PR-2 concurrent (tables x shards) fan-out with the
    PR-1 hot-row cache in front; push rides the non-blocking push
    window (sparse-only — in hybrid mode dense gradients never touch
    the PS), and :meth:`drain` settles it at SSP boundaries.

    Epoch-abandonment contract (docs/ps_recovery.md): when a PS shard
    relaunches (its replies carry a new ``shard_epoch``), the client
    behind this plane invalidates that shard's cache entries and
    ABANDONS the in-flight push window — :meth:`drain` drops those
    pushes' outcomes (never resends, never wedges on their failures),
    exactly like the round-requeue contract drops a requeued task's
    prefetched pull (:class:`EmbeddingPullPipeline.invalidate`): work
    addressed to a dead incarnation is dropped once, not replayed.
    """

    name = "ps"

    def __init__(self, ps_client):
        self._client = ps_client

    @property
    def client(self):
        return self._client

    @property
    def cache(self):
        return getattr(self._client, "hot_row_cache", None)

    def pull(self, ids_by_table):
        return self._client.pull_embedding_vectors_multi(ids_by_table)

    def push(self, sparse_tensors, version):
        # dense side empty by contract: the hybrid trainer keeps dense
        # parameters out of the PS round trip entirely
        return self._client.push_gradient({}, sparse_tensors, version)

    def drain(self):
        if hasattr(self._client, "drain"):
            return self._client.drain()
        return True, -1

    def close(self):
        if hasattr(self._client, "close"):
            self._client.close()


class MasterStorePlane(CommPlane):
    """The master-KV store plane (the reference's non-PS deployment).

    The master holds one process-local store, so pulls are per-table
    RPCs on the blocking control channel and sparse pushes travel WITH
    the dense gradients in ``report_gradient`` (the worker owns that
    combined push; :meth:`push` is therefore unsupported here).
    ``stub_fn`` resolves the master stub at call time — workers may be
    handed their stub after construction (tests, the in-process rung).
    """

    name = "ps"  # same host-pull semantics; storage differs

    def __init__(self, stub_fn):
        self._stub_fn = stub_fn

    def pull(self, ids_by_table):
        stub = self._stub_fn()
        return {
            name: np.asarray(
                stub.pull_embedding_vectors(name, ids), dtype=np.float32
            )
            for name, ids in ids_by_table.items()
        }

    def push(self, sparse_tensors, version):
        raise NotImplementedError(
            "master-store sparse gradients ride report_gradient with "
            "the dense tensors; push() has no separate wire path here"
        )


class HbmPlane(CommPlane):
    """The in-mesh HBM plane: the table is a sharded model parameter
    and the lookup/update run INSIDE the jitted step (nn/hbm_embedding:
    a2a row routing with the in-graph ``padded_unique`` dedup — the
    jit-side twin of this interface's host planner). ``pull``/``push``
    therefore never execute: the plane exists so hybrid planners can
    treat every table uniformly, and so a shared cache can front HBM
    tables on serving workers later (ROADMAP item 3)."""

    name = "hbm"
    in_graph = True

    def __init__(self, shared_cache=None):
        self._cache = shared_cache

    @property
    def cache(self):
        return self._cache

    def pull(self, ids_by_table):
        raise RuntimeError(
            "hbm tables are looked up inside the jitted step (a2a "
            "collective); there is no host-side pull to perform"
        )

    def push(self, sparse_tensors, version):
        raise RuntimeError(
            "hbm table gradients apply inside the jitted step; there "
            "is no host-side push to perform"
        )


# ---------------------------------------------------------------------------
# per-table plane selection
# ---------------------------------------------------------------------------


def resolve_table_planes(spec, tables, hybrid_default=None):
    """Parse an ``embedding_plane`` spec into {table_name: plane}.

    Accepted forms:

    - ``"ps"`` / ``"hbm"``: every table on that plane.
    - ``"hybrid"``: per-table via ``hybrid_default`` (the zoo's
      declared split — typically huge tables on ``ps``, small ones in
      the dense/HBM world).
    - ``"table:plane/table:plane"`` explicit per-table entries
      (``/``-separated because ``,`` already delimits model_params);
      unlisted tables get ``ps``.
    """
    tables = list(tables)
    if spec in PLANES:
        return {t: spec for t in tables}
    if spec == "hybrid":
        if not hybrid_default:
            raise ValueError(
                "embedding_plane='hybrid' needs the model to declare a "
                "per-table split (hybrid_default)"
            )
        missing = [t for t in tables if t not in hybrid_default]
        if missing:
            raise ValueError(
                "hybrid plane split missing tables %r" % (missing,)
            )
        return {t: hybrid_default[t] for t in tables}
    out = {t: "ps" for t in tables}
    for entry in str(spec).split("/"):
        entry = entry.strip()
        if not entry:
            continue
        table, sep, plane = entry.partition(":")
        if not sep or plane not in PLANES:
            raise ValueError(
                "bad embedding_plane entry %r (want 'table:ps' or "
                "'table:hbm', '/'-separated; or one of %s, 'hybrid')"
                % (entry, "/".join(PLANES))
            )
        if table not in out:
            raise ValueError(
                "embedding_plane names unknown table %r (model tables: %r)"
                % (table, tables)
            )
        out[table] = plane
    return out


def make_embedding(
    plane,
    output_dim,
    name,
    vocab_size=None,
    mesh=None,
    axis="data",
    mask_zero=False,
    combiner=None,
    collective=False,
    embedding_initializer="uniform",
    **hbm_kwargs,
):
    """Build one table's embedding layer from its plane name.

    ``"ps"`` -> the elastic :class:`~elasticdl_tpu.nn.embedding.
    Embedding` (unbounded vocab, rows pulled per batch, sparse grads
    pushed); ``"hbm"`` -> :class:`~elasticdl_tpu.nn.hbm_embedding.
    HbmEmbedding` (the table is a trainable parameter — vocab-sharded
    over ``mesh[axis]`` when a mesh is given, a plain dense parameter
    in the degenerate mesh=None form, which is exactly how a small
    table lives in the hybrid trainer's dense/allreduce world).
    """
    if plane == "ps":
        from elasticdl_tpu.nn.embedding import Embedding

        return Embedding(
            output_dim=output_dim,
            embedding_initializer=embedding_initializer,
            mask_zero=mask_zero,
            combiner=combiner,
            name=name,
        )
    if plane == "hbm":
        from elasticdl_tpu.nn.hbm_embedding import HbmEmbedding

        if vocab_size is None:
            raise ValueError(
                "hbm-plane table %r needs a declared vocab_size (the "
                "table is a real parameter)" % name
            )
        return HbmEmbedding(
            vocab_size=vocab_size,
            features=output_dim,
            mesh=mesh,
            axis=axis,
            mask_zero=mask_zero,
            collective=collective,
            name=name,
            **hbm_kwargs,
        )
    raise ValueError(
        "unknown embedding plane %r (want one of %s)"
        % (plane, "/".join(PLANES))
    )


# ---------------------------------------------------------------------------
# the overlapped pull (hybrid trainer mode)
# ---------------------------------------------------------------------------


class EmbeddingPullPipeline:
    """One-batch-lookahead fan-out for PS-resident embedding pulls.

    The worker plans batch N+1's lookups on ITS OWN thread (the flax
    id-capture interceptor is not thread-safe to run concurrently with
    a forward) and hands only the PULL — the RTT-heavy
    ``pull_embedding_vectors_multi`` fan-out — to this pipeline's one
    background thread, so the round trip overlaps batch N's jitted
    forward/backward. Concurrency with the worker thread is limited to
    the PSClient surfaces already built for it: the fan-out pool and
    the lock-protected hot-row cache (docs/dense_overlap.md).

    Staleness: a prefetched pull misses at most the worker's OWN
    push for the in-flight batch — one optimizer step of staleness,
    inside the SSP window the hot-row cache and async LR modulation
    already price in (docs/embedding_planes.md).

    Abandonment contract (the round-abandonment race pin): entries are
    keyed by batch object identity, and :meth:`invalidate` drops every
    pending entry EXACTLY ONCE — it waits for the in-flight pull to
    finish (so no RPC is left mutating the cache after the caller moves
    on) and discards the result. A requeued task's prefetched pull is
    therefore dropped once and never served to a later batch; a second
    invalidate (or a consume after invalidate) finds nothing.
    """

    def __init__(self, depth=2):
        self._mu = threading.Lock()
        self._pool = None
        self._depth = max(1, int(depth))
        self._entries = OrderedDict()  # id(batch) -> (batch, plan, future)
        self._closed = False
        self.dropped = 0  # pulls discarded by invalidate()
        self.served = 0  # pulls consumed by the batch they were for

    def _get_pool(self):
        with self._mu:
            if self._closed:
                raise RuntimeError("EmbeddingPullPipeline is closed")
            if self._pool is None:
                # one thread: pulls dispatch in order, and the inner
                # fan-out pool (PSClient) supplies the per-shard
                # concurrency — a second driver would only reorder
                # cache fills
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="edl-emb-pull"
                )
            return self._pool

    def submit(self, key_obj, plan, pull_fn, trace_id=None):
        """Stage ``pull_fn()`` for the batch identified by ``key_obj``.

        ``plan`` rides alongside so the consumer gets back exactly the
        lookups the pull was planned from. Oldest entries beyond the
        lookahead depth are dropped (they can only belong to batches
        the consumer already passed). ``trace_id`` labels the
        background pull's span so the overlapped fan-out shows inside
        the same task trace as the step it hides behind
        (docs/observability.md)."""
        pool = self._get_pool()
        fut = pool.submit(self._traced_pull, pull_fn, trace_id)
        with self._mu:
            self._entries[id(key_obj)] = (key_obj, plan, fut)
            evicted = []
            while len(self._entries) > self._depth:
                evicted.append(self._entries.popitem(last=False))
        for _key, (_, _, old) in evicted:
            self._drop(old)

    @staticmethod
    def _traced_pull(pull_fn, trace_id):
        """Run the staged pull under a span on the pipeline thread —
        the overlap's other half in the trace timeline (the consumer's
        ``step/embedding_pull`` span shows only the blocking tail)."""
        from elasticdl_tpu.utils import profiling

        with profiling.span(
            "step/embedding_pull_bg", trace_id=trace_id, pipelined=True
        ):
            return pull_fn()

    def consume(self, key_obj):
        """(plan, pulled_rows) staged for this batch, or None.

        Blocks on the in-flight pull when it has not landed yet — that
        wait is the tail of the overlapped round trip."""
        with self._mu:
            entry = self._entries.pop(id(key_obj), None)
        if entry is None:
            return None
        _, plan, fut = entry
        result = fut.result()
        self.served += 1
        return plan, result

    def invalidate(self):
        """Drop every pending prefetched pull; returns how many.

        Waits each future out (a discarded pull must not keep touching
        the shared cache after the caller has moved on) and swallows
        its errors — an abandoned batch's failed pull is nobody's
        problem."""
        with self._mu:
            entries, self._entries = list(self._entries.values()), (
                OrderedDict()
            )
        for _, _, fut in entries:
            self._drop(fut)
        return len(entries)

    def _drop(self, fut):
        try:
            fut.result()
        except Exception:  # noqa: BLE001 — abandoned pull, outcome moot
            from elasticdl_tpu.common.log_utils import default_logger

            default_logger.debug(
                "abandoned prefetched embedding pull failed; dropped",
                exc_info=True,
            )
        self.dropped += 1

    def close(self):
        self.invalidate()
        with self._mu:
            self._closed = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)
