"""Uniform interface over user flax modules.

The model-zoo contract (reference model_zoo/*, e.g.
mnist_functional_api.py:8-26) produces a model object; here that object is a
flax ``nn.Module`` whose ``__call__(features, training=False)`` takes the
element produced by the user's ``dataset_fn`` (an array or a dict of
arrays). This module centralizes the variable-collection plumbing so the
rest of the framework treats a model as two pytrees:

- ``params``  — trainable (differentiated, shipped as gradients)
- ``state``   — non-trainable collections (batch_stats etc.), updated by
  the forward pass in training mode

which mirrors the reference's trainable/non-trainable variable split
(common/model_utils.py:167-183).
"""

import jax


def init_variables(module, rng, features):
    """One tracing forward pass to create variables.

    Parity: the reference creates variables with a throwaway eager forward
    pass before reporting them to the master/PS (worker.py:489-526).
    """
    params_rng, dropout_rng = jax.random.split(jax.random.PRNGKey(rng) if isinstance(rng, int) else rng)
    return module.init(
        {"params": params_rng, "dropout": dropout_rng},
        features,
        training=False,
    )


def split_variables(variables):
    """variables -> (params, state) where state is every other collection."""
    variables = dict(variables)
    params = variables.pop("params", {})
    return params, variables


def merge_variables(params, state):
    return {"params": params, **(state or {})}


def apply_model(module, params, state, features, training=False, rng=None):
    """Forward pass. Returns ``(output, new_state)``.

    In training mode, non-param collections (batch_stats, ...) are mutable
    and their updated values are returned; dropout draws from ``rng``.
    """
    variables = merge_variables(params, state)
    rngs = {"dropout": rng} if rng is not None else None
    mutable = list(state.keys()) if (training and state) else False
    if mutable:
        output, new_state = module.apply(
            variables, features, training=training, rngs=rngs, mutable=mutable
        )
        return output, dict(new_state)
    output = module.apply(variables, features, training=training, rngs=rngs)
    return output, state
