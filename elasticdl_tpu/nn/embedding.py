"""Elastic embedding layer (unbounded vocab, externally stored rows).

Parity: reference elasticdl/layers/embedding.py — a layer whose table
lives outside the worker (sharded PS / master KV), pulling only the rows a
batch touches and pushing sparse row gradients back; supports mask_zero
and sum/mean/sqrtn combiners.

TPU-native redesign: the reference escapes the graph with
``tf.py_function(lookup)`` per call (embedding.py:234-236), which would
defeat jit/XLA. Here the lookup is *hoisted out of the compiled step*:

1. the worker captures each elastic layer's ids on host with a flax
   method interceptor (:func:`capture_embedding_ids`) — no RPC, no real
   compute needed (the layer is short-circuited to zeros),
2. unique rows are pulled from the store and padded to a power-of-two
   bucket (bounds XLA recompiles across varying unique-id counts),
3. the jitted step receives rows via the ``edl_embedding`` collection and
   position indices via ``edl_embedding_idx``; inside the graph the layer
   is a pure static-shape gather — MXU/VPU friendly, nothing leaves HBM,
4. gradients w.r.t. the rows collection come out of ``value_and_grad``
   batched per layer — the BET (batch-embedding-tensor) analog
   (reference worker.py:358-377) — and ship as IndexedSlices frames.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

ROWS_COLLECTION = "edl_embedding"
IDX_COLLECTION = "edl_embedding_idx"


class _CallSlot(nn.Module):
    """Per-call position indices for one :class:`Embedding` call site.

    A layer called N times per forward owns N slots, named explicitly in
    call order (flax's auto-numbering cannot be used: it resets per
    invocation — that reset IS module sharing, which would alias both
    calls onto one idx buffer). All slots gather from the SAME rows
    buffer, so a tied/reused embedding shares one table and its row
    gradients accumulate across calls (the reference instead degrades
    such models to eager, reference worker.py:514-524)."""

    @nn.compact
    def __call__(self, ids, rows):
        idx = self.variable(
            IDX_COLLECTION,
            "idx",
            lambda: jnp.zeros(ids.shape, jnp.int32),
        ).value
        return jnp.take(rows, idx, axis=0)  # ids.shape + (dim,)


def call_slot_name(i):
    """The flax auto-name of the i-th Embedding call's idx slot; the
    worker keys per-call idx arrays under ``path + (call_slot_name(i),)``."""
    return "_CallSlot_%d" % i


_SLOT_WRAP_WARNED = set()  # layer paths already warned (once per process)


class Embedding(nn.Module):
    """Elastic embedding: rows are per-batch inputs, not parameters.

    ``output_dim`` is the embedding dimension; the vocabulary is unbounded
    (rows materialize lazily in the store, ps/embedding_table.py).
    """

    output_dim: int
    embedding_initializer: str = "uniform"
    mask_zero: bool = False
    input_length: int = None
    combiner: str = None

    @nn.compact
    def __call__(self, ids, training=False):
        ids = jnp.asarray(ids).astype(jnp.int32)
        rows = self.variable(
            ROWS_COLLECTION,
            "rows",
            lambda: jnp.zeros((1, self.output_dim), jnp.float32),
        ).value
        # per-call slot index: a plain counter on the bound instance —
        # fresh per apply (linen re-binds a new clone each apply), and
        # monotonic across repeated calls within one forward
        call_index = getattr(self, "_edl_call_index", 0)
        object.__setattr__(self, "_edl_call_index", call_index + 1)
        # a long-lived `module.bind(variables)` handle reuses ONE
        # instance across forwards, so the counter outlives the slots;
        # wrap onto the bound slot count (within a single forward the
        # collection holds exactly one slot per call, so this never
        # fires there — it only folds bound-handle reuse back to slot
        # 0). Skipped during init, where slots are still accruing and
        # self.variables grows one slot per call. Known trade-off: an
        # UNDER-provisioned collection (fewer slots than calls, e.g. a
        # hand-built single-slot idx tree for a twice-calling model)
        # also wraps instead of raising — indistinguishable from bound
        # reuse; every framework path provisions the full slot count
        # from the capture pass, so only hand-built collections can
        # trip this.
        if self.scope is not None and not self.is_initializing():
            n_slots = len(self.variables.get(IDX_COLLECTION, {}))
            if n_slots and call_index >= n_slots:
                self._warn_slot_wrap(call_index, n_slots)
                call_index %= n_slots
        emb = _CallSlot(name=call_slot_name(call_index))(ids, rows)
        if self.mask_zero:
            emb = emb * (ids != 0).astype(emb.dtype)[..., None]
        if self.combiner is not None:
            if self.mask_zero:
                counts = jnp.maximum(
                    (ids != 0).sum(axis=-1, keepdims=True), 1
                ).astype(emb.dtype)
            else:
                counts = jnp.full((ids.shape[0], 1), ids.shape[-1], emb.dtype)
            total = emb.sum(axis=-2)
            if self.combiner == "sum":
                emb = total
            elif self.combiner == "mean":
                emb = total / counts
            elif self.combiner == "sqrtn":
                emb = total / jnp.sqrt(counts)
            else:
                raise ValueError("Unknown combiner %r" % self.combiner)
        return emb

    def _warn_slot_wrap(self, call_index, n_slots):
        """Once-per-layer notice when the call-slot counter wraps.

        Wrapping is normal for a long-lived ``module.bind`` handle
        (one instance reused across forwards), but it is also the only
        symptom of an UNDER-provisioned hand-built idx collection —
        fewer slots than call sites — where it silently aliases all
        calls onto slot 0 (wrong output). The two are indistinguishable
        here, so say it loudly once instead of failing silently."""
        key = (self.path, n_slots)
        if key in _SLOT_WRAP_WARNED:
            return
        _SLOT_WRAP_WARNED.add(key)
        from elasticdl_tpu.common.log_utils import default_logger

        default_logger.warning(
            "Embedding %s: call %d wrapped onto %d bound slot(s). "
            "Expected for a reused bind() handle; but if this model "
            "calls the layer more than %d time(s) per forward, the idx "
            "collection is under-provisioned (capture with "
            "expected_count or let the framework build it) and lookups "
            "are aliasing onto the wrong slots.",
            "/".join(self.path) if self.path else "<root>",
            call_index,
            n_slots,
            n_slots,
        )


class _CaptureDone(Exception):
    """Internal: aborts the capture forward once all layers reported."""


def capture_embedding_ids(
    module, variables, features, expected_count=None, layer_info=None
):
    """Run one short-circuited host forward; returns {path: [ids, ...]}.

    ``path`` is the module path tuple of each elastic Embedding layer —
    the key under which its rows live in the variable collections; the
    list holds one ids ndarray per CALL of that layer, in call order
    (slot i maps to :func:`call_slot_name`). The layer body is skipped
    (returns zeros), so no rows are needed; when ``expected_count`` — the
    TOTAL number of calls, i.e. idx slots — is given the forward aborts
    as soon as every call has reported, so post-embedding layers never
    execute on host. When a dict is passed as ``layer_info`` it is
    filled with {path: (output_dim, embedding_initializer)} so callers
    can register tables with the layer-declared initializer (the
    reference forwards it in EmbeddingTableInfo, elasticdl.proto:76-80).
    """
    captured = {}
    n_calls = 0

    def interceptor(next_fun, args, kwargs, context):
        nonlocal n_calls
        if (
            isinstance(context.module, Embedding)
            and context.method_name == "__call__"
        ):
            ids = np.asarray(args[0])
            path = context.module.path
            captured.setdefault(path, []).append(ids)
            n_calls += 1
            if layer_info is not None:
                layer_info[path] = (
                    context.module.output_dim,
                    context.module.embedding_initializer,
                )
            if (
                expected_count is not None
                and n_calls >= expected_count
            ):
                raise _CaptureDone()
            mod = context.module
            out_shape = ids.shape + (mod.output_dim,)
            if mod.combiner is not None:
                out_shape = ids.shape[:-1] + (mod.output_dim,)
            return jnp.zeros(out_shape, jnp.float32)
        return next_fun(*args, **kwargs)

    import jax

    # the early abort raises _CaptureDone THROUGH module.apply, and
    # jax's traceback filtering stats every frame's file against its
    # package dirs on the way out — ~110 ms of posix.stat per capture,
    # ~60x the actual forward (measured; it dominated the whole PS
    # hot path). Filtering off for the apply makes the abort a plain
    # raise. Process-global config, toggled only around this host-side
    # eager pass: a concurrent thread erroring in this window would
    # merely see an unfiltered traceback.
    prev = jax.config.jax_traceback_filtering
    jax.config.update("jax_traceback_filtering", "off")
    try:
        with nn.intercept_methods(interceptor):
            module.apply(variables, features, training=False)
    except _CaptureDone:
        pass
    finally:
        jax.config.update("jax_traceback_filtering", prev)
    return captured


# The batch-wide dedup planner moved behind the comm-plane interface
# (nn/comm_plane.py) so both embedding planes share it; these names stay
# importable here for the historical call sites.
from elasticdl_tpu.nn.comm_plane import (  # noqa: E402,F401
    plan_lookup,
    plan_lookup_multi,
)


def path_name(path):
    """Collection path tuple -> the store's table/layer name."""
    return "/".join(str(p) for p in path)


def flatten_collection(tree, leaf_name, prefix=()):
    """Nested collection dict -> {path_tuple: array} for ``leaf_name``."""
    out = {}
    for key, value in tree.items():
        if key == leaf_name and not isinstance(value, dict):
            out[prefix] = value
        elif isinstance(value, dict):
            out.update(flatten_collection(value, leaf_name, prefix + (key,)))
    return out


def build_collection(arrays_by_path, leaf_name):
    """{path_tuple: array} -> nested collection dict with ``leaf_name``."""
    tree = {}
    for path, arr in arrays_by_path.items():
        node = tree
        for part in path:
            node = node.setdefault(part, {})
        node[leaf_name] = arr
    return tree
