"""Elastic embedding layer (unbounded vocab, externally stored rows).

Parity: reference elasticdl/layers/embedding.py — a layer whose table
lives outside the worker (sharded PS / master KV), pulling only the rows a
batch touches and pushing sparse row gradients back; supports mask_zero
and sum/mean/sqrtn combiners.

TPU-native redesign: the reference escapes the graph with
``tf.py_function(lookup)`` per call (embedding.py:234-236), which would
defeat jit/XLA. Here the lookup is *hoisted out of the compiled step*:

1. the worker captures each elastic layer's ids on host with a flax
   method interceptor (:func:`capture_embedding_ids`) — no RPC, no real
   compute needed (the layer is short-circuited to zeros),
2. unique rows are pulled from the store and padded to a power-of-two
   bucket (bounds XLA recompiles across varying unique-id counts),
3. the jitted step receives rows via the ``edl_embedding`` collection and
   position indices via ``edl_embedding_idx``; inside the graph the layer
   is a pure static-shape gather — MXU/VPU friendly, nothing leaves HBM,
4. gradients w.r.t. the rows collection come out of ``value_and_grad``
   batched per layer — the BET (batch-embedding-tensor) analog
   (reference worker.py:358-377) — and ship as IndexedSlices frames.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

ROWS_COLLECTION = "edl_embedding"
IDX_COLLECTION = "edl_embedding_idx"


class Embedding(nn.Module):
    """Elastic embedding: rows are per-batch inputs, not parameters.

    ``output_dim`` is the embedding dimension; the vocabulary is unbounded
    (rows materialize lazily in the store, ps/embedding_table.py).
    """

    output_dim: int
    embedding_initializer: str = "uniform"
    mask_zero: bool = False
    input_length: int = None
    combiner: str = None

    @nn.compact
    def __call__(self, ids, training=False):
        ids = jnp.asarray(ids).astype(jnp.int32)
        rows = self.variable(
            ROWS_COLLECTION,
            "rows",
            lambda: jnp.zeros((1, self.output_dim), jnp.float32),
        ).value
        idx = self.variable(
            IDX_COLLECTION,
            "idx",
            lambda: jnp.zeros(ids.shape, jnp.int32),
        ).value
        emb = jnp.take(rows, idx, axis=0)  # ids.shape + (dim,)
        if self.mask_zero:
            emb = emb * (ids != 0).astype(emb.dtype)[..., None]
        if self.combiner is not None:
            if self.mask_zero:
                counts = jnp.maximum(
                    (ids != 0).sum(axis=-1, keepdims=True), 1
                ).astype(emb.dtype)
            else:
                counts = jnp.full((ids.shape[0], 1), ids.shape[-1], emb.dtype)
            total = emb.sum(axis=-2)
            if self.combiner == "sum":
                emb = total
            elif self.combiner == "mean":
                emb = total / counts
            elif self.combiner == "sqrtn":
                emb = total / jnp.sqrt(counts)
            else:
                raise ValueError("Unknown combiner %r" % self.combiner)
        return emb


class _CaptureDone(Exception):
    """Internal: aborts the capture forward once all layers reported."""


def capture_embedding_ids(
    module, variables, features, expected_count=None, layer_info=None
):
    """Run one short-circuited host forward; returns {path: ids ndarray}.

    ``path`` is the module path tuple of each elastic Embedding call —
    the key under which its rows/idx live in the variable collections.
    The layer body is skipped (returns zeros), so no rows are needed; when
    ``expected_count`` is given the forward aborts as soon as every layer
    has reported, so post-embedding layers never execute on host. When a
    dict is passed as ``layer_info`` it is filled with
    {path: (output_dim, embedding_initializer)} so callers can register
    tables with the layer-declared initializer (the reference forwards it
    in EmbeddingTableInfo, elasticdl.proto:76-80).
    """
    captured = {}

    def interceptor(next_fun, args, kwargs, context):
        if (
            isinstance(context.module, Embedding)
            and context.method_name == "__call__"
        ):
            ids = np.asarray(args[0])
            path = context.module.path
            if path in captured:
                raise NotImplementedError(
                    "elastic Embedding %r called more than once per forward"
                    " is not supported (the reference trains such models "
                    "eagerly, worker.py:514-524)" % (path,)
                )
            captured[path] = ids
            if layer_info is not None:
                layer_info[path] = (
                    context.module.output_dim,
                    context.module.embedding_initializer,
                )
            if (
                expected_count is not None
                and len(captured) >= expected_count
            ):
                raise _CaptureDone()
            mod = context.module
            out_shape = ids.shape + (mod.output_dim,)
            if mod.combiner is not None:
                out_shape = ids.shape[:-1] + (mod.output_dim,)
            return jnp.zeros(out_shape, jnp.float32)
        return next_fun(*args, **kwargs)

    try:
        with nn.intercept_methods(interceptor):
            module.apply(variables, features, training=False)
    except _CaptureDone:
        pass
    return captured


def plan_lookup(ids, bucket_min=8):
    """unique ids + per-element positions, padded to a pow2 bucket.

    Returns (unique_ids (k,), idx ids.shape int32, bucket_size).
    Static bucket sizes keep the jitted step's shapes stable across
    batches with different unique-id counts.
    """
    flat = np.asarray(ids).reshape(-1).astype(np.int64)
    unique, inverse = np.unique(flat, return_inverse=True)
    k = len(unique)
    bucket = bucket_min
    while bucket < k:
        bucket *= 2
    idx = inverse.reshape(np.asarray(ids).shape).astype(np.int32)
    return unique, idx, bucket


def path_name(path):
    """Collection path tuple -> the store's table/layer name."""
    return "/".join(str(p) for p in path)


def flatten_collection(tree, leaf_name, prefix=()):
    """Nested collection dict -> {path_tuple: array} for ``leaf_name``."""
    out = {}
    for key, value in tree.items():
        if key == leaf_name and not isinstance(value, dict):
            out[prefix] = value
        elif isinstance(value, dict):
            out.update(flatten_collection(value, leaf_name, prefix + (key,)))
    return out


def build_collection(arrays_by_path, leaf_name):
    """{path_tuple: array} -> nested collection dict with ``leaf_name``."""
    tree = {}
    for path, arr in arrays_by_path.items():
        node = tree
        for part in path:
            node = node.setdefault(part, {})
        node[leaf_name] = arr
    return tree
