"""Vocab-sharded embedding table resident in device HBM.

This is the TPU-native replacement for the reference's sharded-PS/Redis
embedding plane (BASELINE.json north star: "row-partitioned embedding
tables live in pod HBM with ICI collectives for id lookup/update"):

- the table is a *regular trainable parameter* sharded on its vocab axis
  across a mesh axis (``P(axis, None)``); optimizer state co-shards
  automatically under jit, mirroring the PS slot-table co-location
  (reference ps/parameters.py:145-159) with zero extra machinery,
- lookup runs under shard_map: every device gathers the rows it owns for
  the (replicated) id batch and a ``psum`` over ICI assembles the full
  activation — communication is O(B x L x D), independent of vocab size,
- gradients flow through the shard_map transpose: each device receives
  exactly its shard's row gradients, so the update never materializes the
  dense (V, D) gradient anywhere.

The host-PS mode (nn/embedding.py + ps/) remains for CPU-RAM-sized tables
and async training; both share checkpoint naming via the params pytree.
Both planes implement the comm-plane interface (nn/comm_plane.py,
docs/embedding_planes.md) — this one as the ``in_graph`` plane, whose
"pull" is the a2a collective itself and whose dedup planner is the
jit-side :func:`~elasticdl_tpu.nn.sparse_comms.padded_unique` twin of
the host planner — so one model may mix planes per table
(``comm_plane.make_embedding``), e.g. a hybrid deepfm with its huge
feature table on the PS fleet and this layer's small tables living as
ordinary dense-world parameters.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.nn.sparse_comms import padded_unique
from elasticdl_tpu.parallel.ring_attention import shard_map


METRICS_COLLECTION = "metrics"
OVERFLOW_METRIC = "a2a_overflow"


def a2a_overflow_total(state):
    """Total overflowed-id count across every HbmEmbedding in ``state``.

    Sums the ``metrics/*/a2a_overflow`` counters the layers accumulate
    (see :class:`HbmEmbedding`); returns None when the model has no such
    counters. Accepts device or host pytrees — callers fetch per leaf,
    so the cost is a scalar transfer per embedding layer.
    """
    if not isinstance(state, dict) or METRICS_COLLECTION not in state:
        return None
    total = 0
    found = False

    def walk(node):
        nonlocal total, found
        if hasattr(node, "items"):
            for k, v in node.items():
                if k == OVERFLOW_METRIC:
                    found = True
                    # replicated counter: every shard holds the global
                    # value, so read this process's replica rather than
                    # summing copies (device_get of a non-addressable
                    # multi-host array would fail)
                    if hasattr(v, "addressable_shards"):
                        arr = np.asarray(v.addressable_shards[0].data)
                    else:
                        arr = np.asarray(jax.device_get(v))
                    total += int(arr.reshape(-1)[0])
                else:
                    walk(v)

    walk(state[METRICS_COLLECTION])
    return total if found else None


def psum_lookup_collective(table_local, ids, axis):
    """Gather+psum body for one device; ``axis`` must already be bound
    (call inside shard_map / an outer collective step).

    ``table_local``: this device's (V/n, D) table shard; ``ids``: this
    device's id slice, any shape. Returns ids.shape + (D,)."""
    me = jax.lax.axis_index(axis)
    rows_per = table_local.shape[0]
    local = ids.astype(jnp.int32) - me * rows_per
    mask = (local >= 0) & (local < rows_per)
    safe = jnp.clip(local, 0, rows_per - 1)
    rows = jnp.take(table_local, safe, axis=0)
    rows = jnp.where(mask[..., None], rows, 0)
    return jax.lax.psum(rows, axis)


def _check_divisible(table, mesh, axis):
    """Uneven vocab shards would fail deep inside shard_map tracing with
    an opaque message; fail here with an actionable one instead. On the
    elastic plane the same check runs at establish() against the NEW
    world size (parallel/elastic.py), where it matters most: a re-form
    to a non-divisor size must error clearly, not crash-loop."""
    n = mesh.shape[axis]
    if table.shape[0] % n:
        raise ValueError(
            "embedding vocab_size %d is not divisible by mesh axis "
            "%r size %d; pad the table rows to the next multiple "
            "(e.g. vocab_size=%d) so every device holds an equal shard"
            % (table.shape[0], axis, n, -(-table.shape[0] // n) * n)
        )


def sharded_lookup(table, ids, mesh, axis):
    """Gather rows of a vocab-sharded table; differentiable.

    ``table``: global (V, D) sharded P(axis, None); ``ids``: int array of
    any shape. Returns ids.shape + (D,).

    When the mesh also has a ``data`` axis distinct from the table axis,
    the id batch (and the output) shard over it, so each dp replica only
    gathers/psums its own batch slice and the psum rides the table axis
    alone. On a mesh where the table axis IS the batch axis (pure-dp), ids
    must replicate across it — the collective then carries the global
    batch, which is the unavoidable cost of vocab-sharding over the same
    axis as the batch; shard tables on ``model`` to avoid it.
    """

    _check_divisible(table, mesh, axis)

    def _lookup(table_local, ids):
        return psum_lookup_collective(table_local, ids, axis)

    axes = set(mesh.axis_names)
    batch_axis = "data" if ("data" in axes and axis != "data") else None
    ids_spec = P(*([batch_axis] + [None] * (ids.ndim - 1)))
    out_spec = P(*([batch_axis] + [None] * ids.ndim))
    return shard_map(
        _lookup,
        mesh=mesh,
        in_specs=(P(axis, None), ids_spec),
        out_specs=out_spec,
        check_rep=False,
    )(table, ids)


def a2a_lookup_collective(
    table_local, ids_flat, axis, capacity=None, return_overflow=False
):
    """all_to_all routing body for one device; ``axis`` must already be
    bound (call inside shard_map / an outer collective step).

    ``table_local``: this device's (V/n, D) shard; ``ids_flat``: this
    device's flat id slice. Negative ids are SKIP slots (the
    :func:`~elasticdl_tpu.nn.sparse_comms.padded_unique` padding): they
    consume no per-peer capacity, read zero rows, and are never counted
    as overflow. Returns (ids, D) — or, with ``return_overflow=True``,
    ``(rows, n_overflowed)`` where ``n_overflowed`` is this device's
    LOCAL count of live ids that didn't fit their per-peer capacity
    bucket and therefore read zero rows. The caller owns aggregation,
    because only it knows how ids were spread: psum over ``axis`` when
    each device routed a distinct slice (the elastic plane), no-op when
    the ids were replicated (each device already counted the whole
    batch). See :func:`all_to_all_lookup` for the routing/capacity
    semantics."""
    n = jax.lax.psum(1, axis)
    me = jax.lax.axis_index(axis)
    rows_per = table_local.shape[0]
    mm = ids_flat.shape[0]  # ids local to this batch shard
    cap = mm if capacity is None else min(capacity, mm)

    live = ids_flat >= 0
    owner = jnp.clip(ids_flat // rows_per, 0, n - 1)
    # skip slots bucket past every real peer (owner n) so they sort to
    # the end and cannot displace live ids from their capacity windows
    owner = jnp.where(live, owner, n)
    order = jnp.argsort(owner, stable=True)
    sorted_owner = owner[order]
    sorted_ids = ids_flat[order]
    sorted_live = live[order]
    counts = jnp.bincount(owner, length=n + 1)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(mm) - starts[sorted_owner]
    ok = (pos < cap) & sorted_live
    # overflow and skip entries write to a trash column (cap) so they
    # can't clobber a live slot; the buffer is sliced back to cap below
    pos = jnp.where(ok, pos, cap)
    write_owner = jnp.minimum(sorted_owner, n - 1)

    # (n, cap) send buffers: row p holds the ids this device asks
    # peer p for; invalid slots carry id -1
    send_ids = jnp.full((n, cap + 1), -1, jnp.int32)
    send_ids = send_ids.at[write_owner, pos].set(sorted_ids)[:, :cap]
    pos = jnp.where(ok, pos, 0)
    recv_ids = jax.lax.all_to_all(
        send_ids, axis, split_axis=0, concat_axis=0, tiled=True
    )  # row p = ids peer p asked me for

    local = recv_ids - me * rows_per
    valid = (local >= 0) & (local < rows_per)
    rows = jnp.take(
        table_local, jnp.clip(local, 0, rows_per - 1), axis=0
    )
    rows = jnp.where(valid[..., None], rows, 0)
    back = jax.lax.all_to_all(
        rows, axis, split_axis=0, concat_axis=0, tiled=True
    )  # row p = rows for the ids I sent to peer p

    out_sorted = back[write_owner, pos]
    out_sorted = jnp.where(ok[..., None], out_sorted, 0)
    inv = jnp.argsort(order, stable=True)
    out = out_sorted[inv]
    if not return_overflow:
        return out
    n_over = jnp.sum(sorted_live & ~ok).astype(jnp.int32)
    return out, n_over


def a2a_dedup_lookup_collective(
    table_local, ids_flat, axis, capacity=None, return_overflow=False
):
    """Dedup-before-comm variant of :func:`a2a_lookup_collective`.

    Batch-wide unique ids (static-shape :func:`padded_unique`) are the
    only thing routed over the ``axis`` ring; per-occurrence rows are
    restored by a LOCAL gather through the inverse map. The gather's
    transpose is a scatter-add over the inverse map, so the backward
    all_to_all also carries exactly one combined gradient row per
    unique id — with k unique ids in an m-id batch both wire directions
    shrink by m/k. ``capacity`` therefore bounds UNIQUE ids per peer
    here; a duplicate-heavy batch needs proportionally less of it.
    Overflow counts unique ids dropped (each dropped unique id zeroes
    every occurrence that maps to it)."""
    uids, inv, _ = padded_unique(ids_flat)
    out = a2a_lookup_collective(
        table_local,
        uids,
        axis,
        capacity=capacity,
        return_overflow=return_overflow,
    )
    if not return_overflow:
        return jnp.take(out, inv, axis=0)
    rows_u, n_over = out
    return jnp.take(rows_u, inv, axis=0), n_over


def all_to_all_lookup(
    table,
    ids,
    mesh,
    axis,
    capacity=None,
    return_overflow=False,
    dedup=False,
):
    """Row exchange by explicit ``all_to_all`` routing (the BASELINE.json
    north-star formulation); differentiable.

    Each device buckets its ids by owning shard (range partition:
    ``owner = id // rows_per_shard``), ships the buckets over the ``axis``
    ring with ``lax.all_to_all``, gathers locally on the owner, and ships
    the rows back. On a mesh with a ``data`` axis distinct from the table
    axis, each dp replica routes only its own id slice, so per-device
    communication is O(capacity x D) — the rows actually requested —
    versus the gather+psum form's O(ids x D) zero-padded reduction, and
    each device's take() only runs over its own requests. On a
    single-axis mesh (table axis == batch axis) the ids replicate and
    this form loses its advantage — use the psum form there
    (``HbmEmbedding(method="auto")`` picks per mesh).

    ``capacity`` bounds the per-peer bucket (static shape). None means the
    exact worst case (every id owned by one shard) — always correct, the
    right choice for tests and modest batches. Production lookups on
    hashed/unique ids set ``capacity ~= 2 x ids/n_shards``; overflowing
    ids fall back to zero rows (same contract as a dropped row in the
    reference's best-effort Redis plane) — size capacity generously. A
    mis-sized capacity is NOT silent: pass ``return_overflow=True`` to
    get ``(rows, n_overflowed)`` back (a replicated global count), which
    :class:`HbmEmbedding` accumulates into its ``metrics/a2a_overflow``
    state counter so workers can alarm on it.

    Backward: the transpose of ``all_to_all`` is ``all_to_all`` and the
    transpose of the owner-side take is a scatter-add into that shard
    alone, so the row gradients route straight back to their owners and
    the dense (V, D) gradient never exists — each device only ever holds
    its own (V/n, D) gradient shard.

    ``dedup=True`` switches to the dedup-before-comm fast path
    (:func:`a2a_dedup_lookup_collective`): each device routes only its
    batch-wide UNIQUE ids and restores per-occurrence rows by a local
    gather through the inverse map, so both wire directions carry one
    row per unique id and ``capacity`` bounds unique ids per peer —
    on duplicate-heavy batches the same correctness holds at a
    fraction of the capacity (and therefore of the ICI traffic).
    """
    _check_divisible(table, mesh, axis)
    orig_shape = ids.shape
    flat = jnp.reshape(jnp.asarray(ids).astype(jnp.int32), (-1,))

    axes = set(mesh.axis_names)
    batch_axis = "data" if ("data" in axes and axis != "data") else None
    body = a2a_dedup_lookup_collective if dedup else a2a_lookup_collective

    def _lookup(table_local, ids_flat):
        out = body(
            table_local,
            ids_flat,
            axis,
            capacity=capacity,
            return_overflow=return_overflow,
        )
        if not return_overflow:
            return out
        rows, n_over = out
        # the local count is replicated along the table axis (every
        # member of that axis routed the same id slice); total across
        # the dp replicas, whose slices are distinct
        if batch_axis is not None:
            n_over = jax.lax.psum(n_over, batch_axis)
        return rows, n_over

    out_spec = P(batch_axis, None)
    out = shard_map(
        _lookup,
        mesh=mesh,
        in_specs=(P(axis, None), P(batch_axis)),
        out_specs=(out_spec, P()) if return_overflow else out_spec,
        check_rep=False,
    )(table, flat)
    if return_overflow:
        rows, n_over = out
        return jnp.reshape(rows, orig_shape + (table.shape[1],)), n_over
    return jnp.reshape(out, orig_shape + (table.shape[1],))


class HbmEmbedding(nn.Module):
    """Drop-in embedding whose table shards over ``mesh[axis]`` HBM.

    ``method``: "auto" (default) picks all_to_all row routing when the
    mesh gives the batch its own axis (where a2a's O(capacity x D) per
    device wins — the north-star formulation) and gather+psum on a
    single-axis mesh (where a2a would replicate the ids and lose);
    "a2a"/"psum" force a form. ``capacity`` tunes the a2a per-peer
    bucket (see :func:`all_to_all_lookup`).

    ``dedup`` (default True) routes only batch-wide unique ids over the
    wire and restores per-occurrence rows (and combines duplicate-row
    gradients) through a local inverse-map gather — the sparse-comms
    fast path (docs/sparse_fast_path.md). With dedup on, ``capacity``
    bounds UNIQUE ids per peer, so power-law batches need far less of
    it. Set ``dedup=False`` to meter raw per-occurrence routing (the
    pre-dedup wire behavior).

    ``collective=True``: for use INSIDE an outer shard_map (the
    multi-process elastic step, parallel/elastic.py) where nesting
    another shard_map is impossible. ``axis`` must be bound by the
    caller; the apply-time table is this device's local shard and the
    ids are the device's batch slice, so the lookup calls the raw
    collective bodies directly. a2a is the natural form here — each
    device routes exactly its local ids even when the table axis IS the
    batch axis. Init still traces densely (no axis bound at init).

    Capacity overflow is metered, not silent: every a2a lookup adds its
    global overflowed-id count to a ``metrics/a2a_overflow`` int32 state
    counter (monotone across steps; replicated, so it survives the
    elastic plane's state averaging unchanged). Read it with
    :func:`a2a_overflow_total`; a nonzero value means ids trained on
    zero rows and ``capacity`` must grow. The counter is only written
    when the ``metrics`` collection is mutable (training steps), so
    frozen-state eval forwards are unaffected.
    """

    vocab_size: int
    features: int
    mesh: object = None
    axis: str = "data"
    mask_zero: bool = False
    method: str = "auto"
    capacity: int = None
    collective: bool = False
    dedup: bool = True

    @nn.compact
    def __call__(self, ids, training=False):
        init = nn.initializers.variance_scaling(
            1.0, "fan_in", "normal", out_axis=0
        )
        if self.collective:
            # self.variable, not self.param: flax shape-validates params
            # against their initializer at apply time, but in collective
            # mode the apply-time value is this device's (V/n, D) LOCAL
            # shard of the declared (V, D) table
            table = self.variable(
                "params",
                "table",
                lambda: init(
                    self.make_rng("params"),
                    (self.vocab_size, self.features),
                ),
            ).value
        else:
            table = self.param(
                "table", init, (self.vocab_size, self.features)
            )
        # declared whenever the caller threads state (init always; the
        # framework step builders pass every collection through), so the
        # state STRUCTURE is identical across init and apply. A bare
        # apply({"params": ...}) with no metrics collection simply goes
        # unmetered instead of erroring.
        overflow = None
        if (
            self.is_initializing()
            or self.has_variable(METRICS_COLLECTION, OVERFLOW_METRIC)
            or self.is_mutable_collection(METRICS_COLLECTION)
        ):
            overflow = self.variable(
                METRICS_COLLECTION,
                OVERFLOW_METRIC,
                lambda: jnp.zeros((), jnp.int32),
            )

        def meter(n_over):
            # init's tracing forward is not a training step: the counter
            # must start at zero
            if (
                overflow is not None
                and not self.is_initializing()
                and self.is_mutable_collection(METRICS_COLLECTION)
            ):
                overflow.value = overflow.value + n_over

        ids = jnp.asarray(ids).astype(jnp.int32)
        if self.collective and not self.is_initializing():
            if self.method == "psum":
                # each device's ids differ inside the outer shard_map, so
                # a psum of per-device lookups would sum MISALIGNED rows
                # — silently wrong activations, not a degraded mode
                raise ValueError(
                    "HbmEmbedding(collective=True) only supports a2a "
                    "routing; psum needs replicated ids, which the "
                    "elastic plane's sharded batch cannot provide"
                )
            flat = jnp.reshape(ids, (-1,))
            body = (
                a2a_dedup_lookup_collective
                if self.dedup
                else a2a_lookup_collective
            )
            out, n_over = body(
                table,
                flat,
                self.axis,
                capacity=self.capacity,
                return_overflow=True,
            )
            # each device routed a distinct batch slice here; psum makes
            # the counter the replicated global total
            meter(jax.lax.psum(n_over, self.axis))
            emb = jnp.reshape(out, ids.shape + (table.shape[1],))
        elif self.mesh is None:
            emb = jnp.take(table, ids, axis=0)
        else:
            table = jax.lax.with_sharding_constraint(
                table, NamedSharding(self.mesh, P(self.axis, None))
            )
            method = self.method
            if method == "auto":
                has_batch_axis = (
                    "data" in self.mesh.axis_names and self.axis != "data"
                )
                method = "a2a" if has_batch_axis else "psum"
            if method == "a2a":
                emb, n_over = all_to_all_lookup(
                    table,
                    ids,
                    self.mesh,
                    self.axis,
                    capacity=self.capacity,
                    return_overflow=True,
                    dedup=self.dedup,
                )
                meter(n_over)
            else:
                emb = sharded_lookup(table, ids, self.mesh, self.axis)
        if self.mask_zero:
            emb = emb * (ids != 0).astype(emb.dtype)[..., None]
        return emb


def table_sharding(mesh, axis="data"):
    """NamedSharding to place an HbmEmbedding table parameter."""
    return NamedSharding(mesh, P(axis, None))
