"""Vocab-sharded embedding table resident in device HBM.

This is the TPU-native replacement for the reference's sharded-PS/Redis
embedding plane (BASELINE.json north star: "row-partitioned embedding
tables live in pod HBM with ICI collectives for id lookup/update"):

- the table is a *regular trainable parameter* sharded on its vocab axis
  across a mesh axis (``P(axis, None)``); optimizer state co-shards
  automatically under jit, mirroring the PS slot-table co-location
  (reference ps/parameters.py:145-159) with zero extra machinery,
- lookup runs under shard_map: every device gathers the rows it owns for
  the (replicated) id batch and a ``psum`` over ICI assembles the full
  activation — communication is O(B x L x D), independent of vocab size,
- gradients flow through the shard_map transpose: each device receives
  exactly its shard's row gradients, so the update never materializes the
  dense (V, D) gradient anywhere.

The host-PS mode (nn/embedding.py + ps/) remains for CPU-RAM-sized tables
and async training; both share checkpoint naming via the params pytree.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.ring_attention import shard_map


def sharded_lookup(table, ids, mesh, axis):
    """Gather rows of a vocab-sharded table; differentiable.

    ``table``: global (V, D) sharded P(axis, None); ``ids``: int array of
    any shape. Returns ids.shape + (D,).

    When the mesh also has a ``data`` axis distinct from the table axis,
    the id batch (and the output) shard over it, so each dp replica only
    gathers/psums its own batch slice and the psum rides the table axis
    alone. On a mesh where the table axis IS the batch axis (pure-dp), ids
    must replicate across it — the collective then carries the global
    batch, which is the unavoidable cost of vocab-sharding over the same
    axis as the batch; shard tables on ``model`` to avoid it.
    """

    def _lookup(table_local, ids):
        n = jax.lax.psum(1, axis)
        me = jax.lax.axis_index(axis)
        rows_per = table_local.shape[0]
        local = ids.astype(jnp.int32) - me * rows_per
        mask = (local >= 0) & (local < rows_per)
        safe = jnp.clip(local, 0, rows_per - 1)
        rows = jnp.take(table_local, safe, axis=0)
        rows = jnp.where(mask[..., None], rows, 0)
        return jax.lax.psum(rows, axis)

    axes = set(mesh.axis_names)
    batch_axis = "data" if ("data" in axes and axis != "data") else None
    ids_spec = P(*([batch_axis] + [None] * (ids.ndim - 1)))
    out_spec = P(*([batch_axis] + [None] * ids.ndim))
    return shard_map(
        _lookup,
        mesh=mesh,
        in_specs=(P(axis, None), ids_spec),
        out_specs=out_spec,
        check_rep=False,
    )(table, ids)


class HbmEmbedding(nn.Module):
    """Drop-in embedding whose table shards over ``mesh[axis]`` HBM."""

    vocab_size: int
    features: int
    mesh: object = None
    axis: str = "data"
    mask_zero: bool = False

    @nn.compact
    def __call__(self, ids, training=False):
        table = self.param(
            "table",
            nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0
            ),
            (self.vocab_size, self.features),
        )
        ids = jnp.asarray(ids).astype(jnp.int32)
        if self.mesh is None:
            emb = jnp.take(table, ids, axis=0)
        else:
            table = jax.lax.with_sharding_constraint(
                table, NamedSharding(self.mesh, P(self.axis, None))
            )
            emb = sharded_lookup(table, ids, self.mesh, self.axis)
        if self.mask_zero:
            emb = emb * (ids != 0).astype(emb.dtype)[..., None]
        return emb


def table_sharding(mesh, axis="data"):
    """NamedSharding to place an HbmEmbedding table parameter."""
    return NamedSharding(mesh, P(axis, None))
