"""Shared sparse-comms optimizer for both embedding planes.

Recommendation batches (DeepFM-style power-law ID distributions) repeat
the same embedding IDs many times per batch, yet a naive sparse plane
ships every occurrence over the wire: the HBM plane all-to-alls duplicate
rows over ICI and the host-PS plane pulls/pushes duplicate rows over
gRPC. This module holds the primitives both planes use to stop doing
that:

- :func:`padded_unique` — a jit-compatible ``np.unique`` analog with
  static shapes (sorted unique values compacted to the front, -1
  padding after, plus the inverse map). The HBM plane routes only the
  unique slots through ``lax.all_to_all`` and gathers locally through
  the inverse map; the transpose of that local gather is a segment-sum,
  so the BACKWARD wire also carries exactly one gradient row per unique
  id (nn/hbm_embedding.py).
- the host-PS plane's batch planning (nn/embedding.py
  ``plan_lookup_multi``) runs the same dedup on host with ``np.unique``
  before any pull, and the worker combines duplicate gradient rows with
  ``common/tensor.py combine_indexed_slices`` before any push
  (worker/ps_client.py); the hot-row LRU that serves repeated pulls
  locally lives next to the client it accelerates
  (worker/ps_client.py ``HotRowCache``).

See docs/sparse_fast_path.md for the end-to-end picture.
"""

import jax.numpy as jnp


def padded_unique(ids_flat):
    """Jit-compatible unique-with-inverse over a flat int id vector.

    Returns ``(uids, inv, n_unique)`` where ``uids`` has the SAME static
    shape ``(m,)`` as the input — the sorted unique values compacted to
    the front and ``-1`` padding after — ``inv`` maps each input
    position to its slot in ``uids`` (so ``uids[inv]`` reproduces the
    input), and ``n_unique`` is the traced count of live slots.

    The -1 padding is understood by the a2a routing bodies
    (nn/hbm_embedding.py): padded slots consume no per-peer capacity,
    read zero rows, and are never counted as overflow. Gathering the
    routed unique rows back through ``inv`` restores per-occurrence
    rows; the VJP of that gather is a scatter-add over ``inv``, which
    IS the row-combine of duplicate gradients — no separate backward
    pass is needed.
    """
    ids_flat = jnp.asarray(ids_flat)
    m = ids_flat.shape[0]
    if m == 0:
        return ids_flat, jnp.zeros((0,), jnp.int32), jnp.int32(0)
    order = jnp.argsort(ids_flat, stable=True)
    s = ids_flat[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), s[1:] != s[:-1]]
    )
    slot = jnp.cumsum(first) - 1  # unique slot of each sorted element
    uids = jnp.full((m,), -1, ids_flat.dtype).at[slot].set(s)
    inv = (
        jnp.zeros((m,), jnp.int32)
        .at[order]
        .set(slot.astype(jnp.int32))
    )
    return uids, inv, (slot[-1] + 1).astype(jnp.int32)
