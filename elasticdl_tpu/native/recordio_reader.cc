// Native EDLR (indexed record file) reader.
//
// Role parity: the reference's native substrate for shard-addressable data
// is the third-party RecordIO C/Go library (SURVEY.md §2.4); this is the
// framework's own. The file layout is defined in
// elasticdl_tpu/data/recordio.py (the Python writer/reader is the
// portable fallback):
//
//   file   := "EDLR" u32 version  record*  index  tail
//   record := u32 payload_len, u32 crc32(payload), payload bytes
//   index  := u64 count, u64 record_offset[count]
//   tail   := u64 index_offset, "EDLX"
//
// The reader mmaps the file, resolves the index once, and serves
// zero-copy pointers into the mapping — the Python binding wraps them in
// memoryview/bytes. Exposed as a C ABI for ctypes (no pybind11 in this
// toolchain).

#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <zlib.h>

namespace {

constexpr char kMagic[4] = {'E', 'D', 'L', 'R'};
constexpr char kTailMagic[4] = {'E', 'D', 'L', 'X'};
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderSize = 8;   // magic + u32 version
constexpr size_t kTailSize = 12;    // u64 index_offset + tail magic
constexpr size_t kRecHeaderSize = 8;  // u32 len + u32 crc

struct Reader {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t size = 0;
  const uint64_t* offsets = nullptr;  // points into the mapping
  uint64_t count = 0;
};

uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr on any structural error.
void* edlr_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) <
                                 kHeaderSize + kTailSize) {
    ::close(fd);
    return nullptr;
  }
  size_t size = st.st_size;
  void* mapped = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (mapped == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(mapped);
  if (std::memcmp(base, kMagic, 4) != 0 ||
      read_u32(base + 4) != kVersion ||
      std::memcmp(base + size - 4, kTailMagic, 4) != 0) {
    munmap(mapped, size);
    ::close(fd);
    return nullptr;
  }
  // Bounds checks in subtraction form: the additive forms
  // (index_offset + 8, index_offset + 8 + count * 8) wrap around on
  // file-controlled u64 values and would pass on a crafted file.
  uint64_t index_offset = read_u64(base + size - kTailSize);
  if (index_offset > size - kTailSize - 8) {
    munmap(mapped, size);
    ::close(fd);
    return nullptr;
  }
  uint64_t count = read_u64(base + index_offset);
  if (count > (size - kTailSize - index_offset - 8) / 8) {
    munmap(mapped, size);
    ::close(fd);
    return nullptr;
  }
  Reader* r = new Reader();
  r->fd = fd;
  r->base = base;
  r->size = size;
  r->count = count;
  r->offsets = reinterpret_cast<const uint64_t*>(base + index_offset + 8);
  return r;
}

int64_t edlr_num_records(void* handle) {
  if (!handle) return -1;
  return static_cast<Reader*>(handle)->count;
}

// Zero-copy read: *data points into the mapping; valid until edlr_close.
// Returns 0 on success, negative on error.
int edlr_read(void* handle, int64_t index, const uint8_t** data,
              uint32_t* len) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || index < 0 || static_cast<uint64_t>(index) >= r->count) return -1;
  uint64_t off = r->offsets[index];
  // Subtraction form: off / payload_len come from the file and the
  // additive checks wrap on crafted u64/u32 values.
  if (off > r->size - kRecHeaderSize) return -2;
  uint32_t payload_len = read_u32(r->base + off);
  if (payload_len > r->size - off - kRecHeaderSize) return -3;
  *data = r->base + off + kRecHeaderSize;
  *len = payload_len;
  return 0;
}

// CRC-validating read. Returns 0 ok, -4 on checksum mismatch.
int edlr_read_validate(void* handle, int64_t index, const uint8_t** data,
                       uint32_t* len) {
  int rc = edlr_read(handle, index, data, len);
  if (rc != 0) return rc;
  Reader* r = static_cast<Reader*>(handle);
  uint64_t off = r->offsets[index];
  uint32_t expected = read_u32(r->base + off + 4);
  uint32_t actual =
      crc32(0L, reinterpret_cast<const Bytef*>(*data), *len);
  return actual == expected ? 0 : -4;
}

void edlr_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r) return;
  munmap(const_cast<uint8_t*>(r->base), r->size);
  ::close(r->fd);
  delete r;
}

}  // extern "C"
