"""Native (C++) components and their ctypes bindings.

Build with ``python -m elasticdl_tpu.native.build`` (g++ + zlib); loading
falls back silently to the portable Python implementations when the
shared library is absent or ``EDL_DISABLE_NATIVE=1``.
"""

import ctypes
import os

_SO_NAME = "libedl_native.so"
_handle = None
_load_failed = False


def native_lib():
    """The loaded CDLL, or None if unavailable."""
    global _handle, _load_failed
    if _handle is not None or _load_failed:
        return _handle
    if os.environ.get("EDL_DISABLE_NATIVE") == "1":
        _load_failed = True
        return None
    path = os.path.join(os.path.dirname(__file__), _SO_NAME)
    if not os.path.exists(path):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.edlr_open.restype = ctypes.c_void_p
        lib.edlr_open.argtypes = [ctypes.c_char_p]
        lib.edlr_num_records.restype = ctypes.c_int64
        lib.edlr_num_records.argtypes = [ctypes.c_void_p]
        for fn in (lib.edlr_read, lib.edlr_read_validate):
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_uint32),
            ]
        lib.edlr_close.restype = None
        lib.edlr_close.argtypes = [ctypes.c_void_p]
        lib.edlw_create.restype = ctypes.c_void_p
        lib.edlw_create.argtypes = [ctypes.c_char_p]
        lib.edlw_write.restype = ctypes.c_int
        lib.edlw_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.edlw_num_records.restype = ctypes.c_int64
        lib.edlw_num_records.argtypes = [ctypes.c_void_p]
        lib.edlw_close.restype = ctypes.c_int
        lib.edlw_close.argtypes = [ctypes.c_void_p]
        lib.edlw_abort.restype = None
        lib.edlw_abort.argtypes = [ctypes.c_void_p]
        _handle = lib
    except OSError:
        _load_failed = True
    return _handle


class NativeRecordIOReader:
    """ctypes wrapper with the RecordIOReader API (data/recordio.py)."""

    def __init__(self, path):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native library not available")
        self._lib = lib
        self._path = path
        self._h = lib.edlr_open(path.encode())
        if not self._h:
            raise ValueError("not a valid EDLR file: %s" % path)
        self._len = lib.edlr_num_records(self._h)

    def __len__(self):
        return self._len

    def read(self, i, validate=False):
        data = ctypes.POINTER(ctypes.c_ubyte)()
        length = ctypes.c_uint32()
        fn = (
            self._lib.edlr_read_validate
            if validate
            else self._lib.edlr_read
        )
        rc = fn(self._h, i, ctypes.byref(data), ctypes.byref(length))
        if rc == -4:
            raise ValueError(
                "crc mismatch at record %d of %s" % (i, self._path)
            )
        if rc != 0:
            raise IndexError(
                "record %d unreadable in %s (rc=%d)" % (i, self._path, rc)
            )
        return ctypes.string_at(data, length.value)

    def read_range(self, start, end):
        end = min(end, self._len)
        for i in range(max(start, 0), end):
            yield self.read(i)

    def __iter__(self):
        return self.read_range(0, self._len)

    def close(self):
        if self._h:
            self._lib.edlr_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordIOWriter:
    """ctypes wrapper with the RecordIOWriter API (data/recordio.py).

    Errors poison the handle: ``close()`` then refuses to finalize and
    the tail-less file is rejected by both readers as truncated — a
    partial index can never masquerade as a complete file."""

    def __init__(self, path):
        lib = native_lib()
        if lib is None:
            raise RuntimeError("native library not available")
        self._lib = lib
        self._path = path
        self._h = lib.edlw_create(path.encode())
        if not self._h:
            raise OSError("cannot create EDLR file: %s" % path)
        self._closed = False
        self._final_count = 0

    def write(self, payload):
        if self._closed or not self._h:
            raise ValueError("writer is closed")
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise TypeError("record payload must be bytes")
        payload = bytes(payload)
        rc = self._lib.edlw_write(self._h, payload, len(payload))
        if rc != 0:
            raise OSError(
                "EDLR write failed (rc=%d) for %s" % (rc, self._path)
            )

    @property
    def num_records(self):
        if self._h:
            return int(self._lib.edlw_num_records(self._h))
        return self._final_count

    def close(self):
        if self._closed:
            return
        self._closed = True
        h, self._h = self._h, None
        self._final_count = int(self._lib.edlw_num_records(h))
        rc = self._lib.edlw_close(h)
        if rc != 0:
            raise OSError(
                "EDLR finalize failed (rc=%d) for %s; the file has no "
                "tail and readers will reject it" % (rc, self._path)
            )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None and self._h:
            # error path: do NOT finalize a half-written file
            self._closed = True
            h, self._h = self._h, None
            self._lib.edlw_abort(h)
            return
        self.close()

    def __del__(self):
        try:
            if self._h:
                self._lib.edlw_abort(self._h)
                self._h = None
        except Exception:
            pass
