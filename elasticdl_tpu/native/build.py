"""Build the native library: ``python -m elasticdl_tpu.native.build``."""

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))


def build(verbose=True):
    sources = [
        os.path.join(_DIR, "recordio_reader.cc"),
        os.path.join(_DIR, "recordio_writer.cc"),
    ]
    out = os.path.join(_DIR, "libedl_native.so")
    cmd = [
        "g++",
        "-O2",
        "-shared",
        "-fPIC",
        "-std=c++17",
        *sources,
        "-lz",
        "-o",
        out,
    ]
    if verbose:
        print(" ".join(cmd))
    subprocess.check_call(cmd)
    return out


if __name__ == "__main__":
    build()
    sys.exit(0)
