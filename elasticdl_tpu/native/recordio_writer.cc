// Native EDLR (indexed record file) writer.
//
// Role parity: SURVEY.md §2.4 plans a native reader AND writer for the
// shard-addressable record format (the reference leans on the
// third-party RecordIO Go/C library for both sides). The layout is
// defined in elasticdl_tpu/data/recordio.py and shared with
// recordio_reader.cc:
//
//   file   := "EDLR" u32 version  record*  index  tail
//   record := u32 payload_len, u32 crc32(payload), payload bytes
//   index  := u64 count, u64 record_offset[count]
//   tail   := u64 index_offset, "EDLX"
//
// Buffered appends through stdio; close() lands the offset index and
// tail, so a crash mid-write leaves a file without a tail magic that
// both readers reject as truncated. Exposed as a C ABI for ctypes (no
// pybind11 in this toolchain).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <zlib.h>

namespace {

constexpr char kMagic[4] = {'E', 'D', 'L', 'R'};
constexpr char kTailMagic[4] = {'E', 'D', 'L', 'X'};
constexpr uint32_t kVersion = 1;

struct Writer {
  FILE* f = nullptr;
  uint64_t offset = 0;  // current file position (header included)
  std::vector<uint64_t> offsets;
  bool failed = false;
};

bool write_all(Writer* w, const void* data, size_t len) {
  if (std::fwrite(data, 1, len, w->f) != len) {
    w->failed = true;
    return false;
  }
  w->offset += len;
  return true;
}

bool write_u32(Writer* w, uint32_t v) { return write_all(w, &v, 4); }
bool write_u64(Writer* w, uint64_t v) { return write_all(w, &v, 8); }

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr when the file cannot be created.
void* edlw_create(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  if (!write_all(w, kMagic, 4) || !write_u32(w, kVersion)) {
    std::fclose(f);
    delete w;
    return nullptr;
  }
  return w;
}

// Appends one record (length + crc32 + payload). Returns 0 on success,
// negative on error; after any error the writer is poisoned and close()
// will not finalize (the file stays tail-less = unreadable-as-complete).
int edlw_write(void* handle, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w || w->failed) return -1;
  uint32_t crc =
      crc32(0L, reinterpret_cast<const Bytef*>(data), len);
  w->offsets.push_back(w->offset);
  if (!write_u32(w, len) || !write_u32(w, crc) ||
      !write_all(w, data, len)) {
    return -2;
  }
  return 0;
}

int64_t edlw_num_records(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  return static_cast<int64_t>(w->offsets.size());
}

// Finalizes (index + tail) and closes. Returns 0 on success; on any
// prior or current IO failure the tail is never written, so readers
// reject the file as truncated instead of serving a partial index.
int edlw_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  int rc = 0;
  if (!w->failed) {
    uint64_t index_offset = w->offset;
    bool ok = write_u64(w, w->offsets.size());
    for (size_t i = 0; ok && i < w->offsets.size(); ++i) {
      ok = write_u64(w, w->offsets[i]);
    }
    ok = ok && write_u64(w, index_offset) &&
         write_all(w, kTailMagic, 4);
    if (!ok) rc = -2;
  } else {
    rc = -3;
  }
  if (std::fclose(w->f) != 0 && rc == 0) rc = -4;
  delete w;
  return rc;
}

// Close without finalizing (error/abort path): the file keeps no tail.
void edlw_abort(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return;
  std::fclose(w->f);
  delete w;
}

}  // extern "C"
