"""Streaming evaluation metrics (tf.keras.metrics equivalents, numpy/JAX).

Parity: the reference aggregates eval metrics on the master with
``tf.keras.metrics`` objects fed raw model outputs + labels reported by
workers (evaluation_service.py:38-105). Model-zoo ``eval_metrics_fn`` may
return either metric *objects* or plain *callables* ``fn(labels,
predictions) -> per-example values`` (e.g. mnist_functional_api.py:85-91);
both are supported here. Callables are wrapped in a :class:`Mean`.

All metrics are host-side numpy accumulators: they run on the master's CPU
over small reported batches, never inside a jitted step, so they impose no
constraint on XLA compilation.
"""

import numpy as np

__all__ = [
    "Metric",
    "Mean",
    "Sum",
    "Accuracy",
    "BinaryAccuracy",
    "SparseCategoricalAccuracy",
    "CategoricalAccuracy",
    "MeanSquaredError",
    "AUC",
    "as_metric",
]


class Metric:
    """Base streaming metric: update_state / result / reset_states."""

    def __init__(self, name=None):
        self.name = name or type(self).__name__.lower()

    def update_state(self, labels, predictions):
        raise NotImplementedError

    def result(self):
        raise NotImplementedError

    def reset_states(self):
        raise NotImplementedError


class Mean(Metric):
    """Running mean of whatever values are fed in."""

    def __init__(self, name=None, fn=None):
        super().__init__(name)
        self._fn = fn
        self._total = 0.0
        self._count = 0

    def update_state(self, labels, predictions=None):
        if self._fn is not None:
            values = self._fn(labels, predictions)
        else:
            values = labels  # fed values directly
        values = np.asarray(values, dtype=np.float64).reshape(-1)
        self._total += float(values.sum())
        self._count += values.size

    def result(self):
        return self._total / self._count if self._count else 0.0

    def reset_states(self):
        self._total = 0.0
        self._count = 0


class Sum(Metric):
    def __init__(self, name=None):
        super().__init__(name)
        self._total = 0.0

    def update_state(self, labels, predictions=None):
        self._total += float(np.asarray(labels, dtype=np.float64).sum())

    def result(self):
        return self._total

    def reset_states(self):
        self._total = 0.0


class Accuracy(Metric):
    """Exact-match accuracy of predictions vs labels (keras Accuracy)."""

    def __init__(self, name="accuracy"):
        super().__init__(name)
        self._correct = 0
        self._count = 0

    def update_state(self, labels, predictions):
        labels = np.asarray(labels).reshape(-1)
        predictions = np.asarray(predictions).reshape(-1)
        self._correct += int((labels == predictions).sum())
        self._count += labels.size

    def result(self):
        return self._correct / self._count if self._count else 0.0

    def reset_states(self):
        self._correct = 0
        self._count = 0


class SparseCategoricalAccuracy(Metric):
    """argmax(logits) == integer label."""

    def __init__(self, name="accuracy"):
        super().__init__(name)
        self._correct = 0
        self._count = 0

    def update_state(self, labels, predictions):
        labels = np.asarray(labels).reshape(-1)
        pred = np.argmax(np.asarray(predictions), axis=-1).reshape(-1)
        self._correct += int((labels == pred).sum())
        self._count += labels.size

    def result(self):
        return self._correct / self._count if self._count else 0.0

    def reset_states(self):
        self._correct = 0
        self._count = 0


class CategoricalAccuracy(SparseCategoricalAccuracy):
    """argmax(logits) == argmax(one-hot label)."""

    def update_state(self, labels, predictions):
        labels = np.argmax(np.asarray(labels), axis=-1)
        super().update_state(labels, predictions)


class BinaryAccuracy(Metric):
    def __init__(self, name="binary_accuracy", threshold=0.5):
        super().__init__(name)
        self._threshold = threshold
        self._correct = 0
        self._count = 0

    def update_state(self, labels, predictions):
        labels = np.asarray(labels).reshape(-1)
        pred = (np.asarray(predictions).reshape(-1) > self._threshold).astype(
            labels.dtype
        )
        self._correct += int((labels == pred).sum())
        self._count += labels.size

    def result(self):
        return self._correct / self._count if self._count else 0.0

    def reset_states(self):
        self._correct = 0
        self._count = 0


class MeanSquaredError(Metric):
    def __init__(self, name="mse"):
        super().__init__(name)
        self._total = 0.0
        self._count = 0

    def update_state(self, labels, predictions):
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        pred = np.asarray(predictions, dtype=np.float64).reshape(-1)
        self._total += float(((labels - pred) ** 2).sum())
        self._count += labels.size

    def result(self):
        return self._total / self._count if self._count else 0.0

    def reset_states(self):
        self._total = 0.0
        self._count = 0


class AUC(Metric):
    """Streaming ROC AUC via fixed-threshold confusion-count histograms.

    Same approximation scheme as tf.keras.metrics.AUC: bucket scores into
    ``num_thresholds`` bins, accumulate TP/FP/TN/FN per threshold, integrate
    TPR over FPR with the trapezoid rule.
    """

    def __init__(self, name="auc", num_thresholds=200):
        super().__init__(name)
        self._n = num_thresholds
        self._thresholds = np.linspace(0.0, 1.0, num_thresholds)
        self.reset_states()

    def update_state(self, labels, predictions):
        labels = np.asarray(labels).reshape(-1).astype(bool)
        scores = np.asarray(predictions, dtype=np.float64).reshape(-1)
        # predictions >= threshold counted positive, per threshold bin
        pred_pos = scores[None, :] >= self._thresholds[:, None]
        self._tp += (pred_pos & labels[None, :]).sum(axis=1)
        self._fp += (pred_pos & ~labels[None, :]).sum(axis=1)
        self._pos += int(labels.sum())
        self._neg += int((~labels).sum())

    def result(self):
        if not self._pos or not self._neg:
            return 0.0
        tpr = self._tp / self._pos
        fpr = self._fp / self._neg
        # thresholds ascend -> fpr descends; integrate in ascending order
        trapezoid = getattr(np, "trapezoid", None) or np.trapz
        return float(trapezoid(tpr[::-1], fpr[::-1]))

    def reset_states(self):
        self._tp = np.zeros(self._n, dtype=np.int64)
        self._fp = np.zeros(self._n, dtype=np.int64)
        self._pos = 0
        self._neg = 0


def as_metric(name, value):
    """Normalize an eval_metrics_fn dict value into a Metric object.

    Plain callables ``fn(labels, predictions)`` become a Mean over their
    per-example outputs — the contract the reference model zoo relies on
    (mnist_functional_api.py:85-91 returns an elementwise-equality lambda).
    """
    if isinstance(value, Metric):
        return value
    if callable(value):
        return Mean(name=name, fn=value)
    raise TypeError(
        "eval metric %r must be a Metric or callable, got %r" % (name, value)
    )
