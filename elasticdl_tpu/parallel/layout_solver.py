"""Elastic layout re-solve: dp x tp x micro-batch planning on resize.

The elastic plane's original resize model was dp-only: a membership
change re-formed the mesh at the new size with whatever parallelism
layout the job launched with, so an 8 -> 6 -> 8 world either refused to
form (tp doesn't divide) or trained on a badly-shaped mesh. This module
is the ElasWave-style fix (PAPERS.md 2510.00606): given the new world
size, enumerate every feasible ``(dp, tp, micro-batch)`` layout, score
each one — memory-feasible first, then predicted examples/sec — and
hand the winner to ``ElasticDPTrainer.establish`` as the mesh layout.
The marginal-cost reasoning follows "Elastic deep learning in
multi-tenant GPU cluster" (PAPERS.md 1909.11985): the score is
throughput under an explicit cost model, not a heuristic preference
order.

Two scoring regimes share one component decomposition
(compute + dp gradient allreduce + tp activation collectives +
fixed dispatch overhead):

- **static**: a relative FLOP/byte model from the
  :class:`ModelProfile` alone — correct ORDERING for layouts of one
  model on one rig, no absolute-time claims.
- **telemetry-fed**: a measured :class:`StepTelemetry` for a known
  layout re-scales the static components (per component when the
  critical-path breakdown is present, uniformly otherwise), so
  predictions inherit the rig's real constants. tracetool's per-step
  breakdown (``step/compute`` et al.) is the intended source.

Determinism is load-bearing: every process of a consensus world must
solve to the SAME layout or the meshes diverge and the world cannot
form. Therefore (a) `solve` is a pure function of its arguments, (b)
establish-time planning (:meth:`LayoutPlanner.axes_for`) uses only
process-identical inputs — the model profile (derived from the abstract
state), the memory budget (job flag/env), and the world size — never
local telemetry, and (c) ties break on a quantized score, then lower
tp, then higher dp, then larger micro-batch. Telemetry feeds only the
*speculation* ranking (:meth:`LayoutPlanner.candidates`), where a
divergent hedge costs a wasted background compile, not a broken world.

This file must stay jit-free and lock-free by construction (edlint
R7/R8 pin it): the solver runs on the establish path of every process
and inside the speculative compiler's daemon thread, where a stray
lock or device computation would deadlock or wedge a resize.
"""

import math
import os
from dataclasses import dataclass

# Relative-cost constants for the static regime. These are NOT claims
# about the rig (telemetry calibration supplies real constants); they
# only need plausible RATIOS so the static ordering matches the
# telemetry-fed ordering on one model/rig (tests/test_layout_solver.py
# pins that agreement).
_DEVICE_FLOPS = 1.0e12
_ICI_BYTES_PER_S = 1.0e11
_STEP_OVERHEAD_S = 1.0e-3

DEFAULT_MICROBATCHES = (1, 2, 4, 8, 16, 32)


@dataclass(frozen=True)
class Layout:
    """One parallelism layout: dp width x tp degree, and the per-device
    micro-batch (example rows) the step runs at."""

    dp: int
    tp: int
    microbatch: int

    @property
    def n_devices(self):
        return self.dp * self.tp


def mesh_axes_for(layout):
    """The ``mesh_axes`` dict for a layout — always both axes, tp=1
    included: a single-degree model axis keeps the specs (and therefore
    the pjit dense plane and its direct-relayout resize path) active,
    so a dp8xtp1 world is a layout CHANGE, not a plane change."""
    return {"data": int(layout.dp), "model": int(layout.tp)}


@dataclass(frozen=True)
class ModelProfile:
    """Deterministic per-model numbers the cost model needs.

    ``replicated_bytes``: per-device bytes of state that replicates
    (parameters + optimizer slots whose specs don't use ``model``).
    ``tp_bytes``: TOTAL bytes of model-sharded state (each device holds
    ``tp_bytes / tp``). ``activation_bytes_per_row``: relative
    activation volume one example row pushes through the tp collectives.
    ``flops_per_row``: relative compute per example row.
    ``tp_degrees``: the degrees the model admits (every model-sharded
    dimension divides; 1 always included)."""

    replicated_bytes: float
    tp_bytes: float
    activation_bytes_per_row: float
    flops_per_row: float
    tp_degrees: tuple = (1,)


@dataclass(frozen=True)
class StepTelemetry:
    """A measured step on a known layout. ``compute_s``/``dp_comm_s``/
    ``tp_comm_s`` are the PR-13 critical-path phases when available
    (tracetool breakdown); zero means "unmeasured" and the calibration
    falls back to scaling by total step time."""

    layout: Layout
    step_time_s: float
    compute_s: float = 0.0
    dp_comm_s: float = 0.0
    tp_comm_s: float = 0.0


@dataclass(frozen=True)
class ScoredLayout:
    layout: Layout
    feasible: bool
    device_bytes: float
    examples_per_sec: float


def memory_budget_from_env(env=os.environ):
    """Per-device budget in bytes from ``EDL_LAYOUT_MEM_BUDGET_MB``
    (same MiB convention as the bench's EDL_BENCH_DEVICE_BUDGET_MB);
    None when unset/unparseable — every layout memory-feasible."""
    raw = env.get("EDL_LAYOUT_MEM_BUDGET_MB", "")
    try:
        mb = float(raw)
    except ValueError:
        return None
    return mb * (1 << 20) if mb > 0 else None


def device_bytes(layout, profile):
    """Per-device working-set estimate for a layout: replicated state,
    this device's tp shard, and the micro-batch's activations."""
    return (
        float(profile.replicated_bytes)
        + float(profile.tp_bytes) / layout.tp
        + float(profile.activation_bytes_per_row) * layout.microbatch
    )


def _step_components(layout, profile):
    """(compute_s, dp_comm_s, tp_comm_s) under the static constants.

    - compute: per-device rows x flops/row.
    - dp comm: ring-allreduce of this device's gradient bytes,
      ``2 * (dp-1)/dp`` traffic factor; tp shrinks the sharded share.
    - tp comm: per-row activation collectives, ``(tp-1)/tp`` factor.
    """
    rows = layout.microbatch
    compute = rows * float(profile.flops_per_row) / _DEVICE_FLOPS
    grad_bytes = (
        float(profile.replicated_bytes)
        + float(profile.tp_bytes) / layout.tp
    )
    dp_comm = (
        2.0 * grad_bytes * (layout.dp - 1) / layout.dp / _ICI_BYTES_PER_S
        if layout.dp > 1
        else 0.0
    )
    act_bytes = rows * float(profile.activation_bytes_per_row)
    tp_comm = (
        2.0 * act_bytes * (layout.tp - 1) / layout.tp / _ICI_BYTES_PER_S
        if layout.tp > 1
        else 0.0
    )
    return compute, dp_comm, tp_comm


def predict_examples_per_sec(layout, profile, telemetry=None):
    """Predicted global examples/sec for ``layout``.

    With telemetry, the static components re-scale so the measured
    layout's prediction reproduces its measurement: per-component when
    the breakdown is present, else one uniform factor — the uniform
    case preserves the static ordering EXACTLY (a positive scalar on
    every step time), which is the cross-regime agreement the tests
    pin."""
    compute, dp_comm, tp_comm = _step_components(layout, profile)
    overhead = _STEP_OVERHEAD_S
    if telemetry is not None and telemetry.step_time_s > 0:
        m_compute, m_dp, m_tp = _step_components(
            telemetry.layout, profile
        )
        measured_parts = (
            telemetry.compute_s + telemetry.dp_comm_s + telemetry.tp_comm_s
        )
        if measured_parts > 0:
            if telemetry.compute_s > 0 and m_compute > 0:
                compute *= telemetry.compute_s / m_compute
            if telemetry.dp_comm_s > 0 and m_dp > 0:
                dp_comm *= telemetry.dp_comm_s / m_dp
            if telemetry.tp_comm_s > 0 and m_tp > 0:
                tp_comm *= telemetry.tp_comm_s / m_tp
            overhead = max(
                telemetry.step_time_s - measured_parts, 0.0
            )
        else:
            static_step = m_compute + m_dp + m_tp + overhead
            if static_step > 0:
                scale = telemetry.step_time_s / static_step
                compute *= scale
                dp_comm *= scale
                tp_comm *= scale
                overhead *= scale
    step_s = compute + dp_comm + tp_comm + overhead
    if step_s <= 0:
        return 0.0
    return layout.dp * layout.microbatch / step_s


def enumerate_layouts(
    n_devices, profile, microbatches=DEFAULT_MICROBATCHES
):
    """Every (dp, tp, microbatch) with ``dp * tp == n_devices`` and a
    model-admissible tp that divides the world. Deterministic order:
    ascending tp, then ascending micro-batch."""
    n_devices = int(n_devices)
    if n_devices <= 0:
        return []
    degrees = sorted(
        {1}
        | {int(d) for d in (profile.tp_degrees or ()) if int(d) >= 1}
    )
    out = []
    for tp in degrees:
        if n_devices % tp:
            continue
        dp = n_devices // tp
        for mb in microbatches:
            mb = int(mb)
            if mb > 0:
                out.append(Layout(dp=dp, tp=tp, microbatch=mb))
    return out


def _quantized_score(x):
    """Round to 6 significant digits: float noise from a reassociated
    sum must not flip a tie across processes."""
    if x <= 0.0:
        return 0.0
    exp = math.floor(math.log10(x))
    scale = 10.0 ** (exp - 5)
    return round(x / scale) * scale


def _rank_key(scored):
    # feasible first; best quantized score; then the deterministic
    # tie-break: LOWER tp (fewer collectives, simpler failure domain),
    # then higher dp, then larger micro-batch
    return (
        0 if scored.feasible else 1,
        -_quantized_score(scored.examples_per_sec),
        scored.layout.tp,
        -scored.layout.dp,
        -scored.layout.microbatch,
    )


def solve(
    n_devices,
    profile,
    memory_budget=None,
    microbatches=DEFAULT_MICROBATCHES,
    telemetry=None,
):
    """Ranked :class:`ScoredLayout` list for a world of ``n_devices``.

    Memory-feasible layouts rank strictly before infeasible ones (the
    infeasible tail is kept — the caller may report WHY nothing fits).
    A pure function: identical inputs produce the identical ranking on
    every process."""
    scored = [
        ScoredLayout(
            layout=layout,
            feasible=(
                memory_budget is None
                or device_bytes(layout, profile) <= memory_budget
            ),
            device_bytes=device_bytes(layout, profile),
            examples_per_sec=predict_examples_per_sec(
                layout, profile, telemetry
            ),
        )
        for layout in enumerate_layouts(n_devices, profile, microbatches)
    ]
    scored.sort(key=_rank_key)
    return scored


def best(
    n_devices,
    profile,
    memory_budget=None,
    microbatches=DEFAULT_MICROBATCHES,
    telemetry=None,
):
    """The winning feasible layout, or None when no layout exists for
    this world size at all (no admissible tp divides it)."""
    ranked = solve(
        n_devices, profile, memory_budget, microbatches, telemetry
    )
    for s in ranked:
        if s.feasible:
            return s
    return ranked[0] if ranked else None


class LayoutPlanner:
    """The trainer-facing planning surface.

    Wraps a zoo's static ``mesh_axes`` hook: until a model profile is
    fed (:meth:`set_profile`, derived from the first establish's
    abstract state), :meth:`axes_for` answers with the static fallback;
    after that, every resize re-solves the layout. ``axes_for`` is
    deliberately telemetry-blind (see the module docstring);
    :meth:`candidates` ranks the speculation hedge with the latest
    local telemetry, but always leads with the deterministic winner —
    the layout establish will actually pick."""

    def __init__(
        self,
        fallback_axes_fn=None,
        memory_budget=None,
        microbatches=DEFAULT_MICROBATCHES,
    ):
        self.fallback_axes_fn = fallback_axes_fn
        self.memory_budget = (
            memory_budget
            if memory_budget is not None
            else memory_budget_from_env()
        )
        self.microbatches = tuple(int(m) for m in microbatches)
        self.profile = None
        self.telemetry = None
        self.last_plan = None  # the most recent establish-path pick

    def set_profile(self, profile):
        self.profile = profile

    def set_telemetry(self, telemetry):
        """Feed a measured step (speculation ranking only)."""
        self.telemetry = telemetry

    def plan(self, n_devices):
        """Deterministic establish-path pick (no telemetry), or None
        when no profile has been fed / no layout forms."""
        if self.profile is None:
            return None
        pick = best(
            n_devices,
            self.profile,
            self.memory_budget,
            self.microbatches,
        )
        if pick is not None:
            self.last_plan = pick
        return pick

    def axes_for(self, n_devices):
        """``mesh_axes_fn`` drop-in for :class:`ElasticDPTrainer`."""
        pick = self.plan(n_devices)
        if pick is None:
            return (
                self.fallback_axes_fn(n_devices)
                if self.fallback_axes_fn
                else None
            )
        return mesh_axes_for(pick.layout)

    def candidates(self, n_devices, top=2):
        """Top ``top`` distinct (dp, tp) layouts for speculation hints:
        the deterministic winner first, telemetry-ranked hedges after."""
        if self.profile is None:
            return []
        out, seen = [], set()

        def take(scored):
            key = (scored.layout.dp, scored.layout.tp)
            if scored.feasible and key not in seen:
                seen.add(key)
                out.append(scored.layout)

        winner = self.plan(n_devices)
        if winner is not None and winner.feasible:
            take(winner)
        for s in solve(
            n_devices,
            self.profile,
            self.memory_budget,
            self.microbatches,
            telemetry=self.telemetry,
        ):
            if len(out) >= top:
                break
            take(s)
        return out[:top]
