"""Multi-process world lifecycle: the TPU-native membership substrate.

The reference's distributed fabric is k8s-Service-DNS discovery plus
gRPC channels that tolerate peers coming and going
(reference common/k8s_client.py:89-97, docs/designs/parameter_server.md:
106-107). The TPU equivalent (SURVEY.md §2.3) is a ``jax.distributed``
process world: a coordination service hosted by rank 0, every process
holding a slot in one global device mesh, and XLA collectives riding
ICI/DCN between them.

Elasticity requires *re-forming* that world when membership changes. XLA
worlds are static per initialization, so a membership epoch is:

    leave_world()  ->  ensure_world(new_spec)

which tears down the coordination client, drops every initialized backend
(their device objects are invalid in the new world), and re-initializes
with the new rank/size/coordinator. Device state must be pulled to host
before leaving and re-placed after (parallel/elastic.py does this for the
train state).

Worlds are described by :class:`WorldSpec`, handed out by the master's
MembershipService over the control-plane RPC — the master is the single
source of membership truth, exactly as it is for task dispatch.

CPU bring-up: set ``EDL_DIST_PLATFORM=cpu`` (tests, local multi-process
jobs) to run the same code path over gloo TCP collectives with
``EDL_LOCAL_DEVICES`` virtual devices per process.
"""

import os
from dataclasses import dataclass

from elasticdl_tpu.common.log_utils import default_logger as logger


@dataclass(frozen=True)
class WorldSpec:
    """One membership epoch's process world."""

    coordinator: str  # host:port of rank 0's coordination service
    num_processes: int
    process_id: int
    epoch: int

    def singleton(self):
        return self.num_processes <= 1


class WorldBroken(RuntimeError):
    """A collective or coordination failure that requires re-forming."""


# Must sit BELOW the master's confirm/fence window (MembershipService
# confirm_timeout_secs, default 15): a member stuck in a stale formation
# barrier has to fail fast (WorldBroken -> re-poll, self-recovery) before
# the fencer declares it wedged and kills the healthy process. Healthy
# formations complete in well under a second (members only enter the
# barrier after the two-phase confirm). Shared with the master's
# staleness valve, which must outlast one full initialize timeout.
DEFAULT_WORLD_INIT_TIMEOUT = 10


def world_init_timeout():
    return int(
        os.environ.get(
            "EDL_WORLD_INIT_TIMEOUT", str(DEFAULT_WORLD_INIT_TIMEOUT)
        )
    )


_active_spec = None

# Monotonic count of backend teardowns in this process. Any compiled
# executable (or cached jitted callable bound to concrete devices) minted
# before the latest bump holds dead device handles; the compile plane's
# ExecutableCache keys on this so stale entries are evicted, never reused.
_backend_epoch = 0


def current_spec():
    return _active_spec


def backend_epoch():
    return _backend_epoch


def _bump_backend_epoch():
    global _backend_epoch
    _backend_epoch += 1


def _configure_platform():
    """Apply env-selected platform before the backend initializes.

    Env vars are not enough here: a sitecustomize may pre-register an
    accelerator plugin and pin ``jax_platforms`` via jax.config at
    interpreter startup, so the override must go through jax.config (same
    reasoning as tests/conftest.py).
    """
    import jax

    # a dead peer must surface as a catchable error in the survivors, not
    # a process-killing propagated fatal — survivors re-form instead
    try:
        jax.config.update("jax_enable_recoverability", True)
    except AttributeError:  # older jax without the flag
        pass
    if os.environ.get("EDL_DIST_PLATFORM") == "cpu":
        n = os.environ.get("EDL_LOCAL_DEVICES")
        if n:
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=" + n
                ).strip()
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    else:
        # an explicit JAX_PLATFORMS must survive a sitecustomize's
        # config pin here too — the world re-forms drop and re-create
        # backends, and each re-create re-resolves the platform
        from elasticdl_tpu.common.jax_platform import (
            honor_jax_platforms_env,
        )

        honor_jax_platforms_env()


def _clear_backends():
    import jax

    try:
        from jax.extend.backend import clear_backends
    except ImportError:  # older jax
        clear_backends = getattr(jax, "clear_backends", None)
    if clear_backends is not None:
        clear_backends()
    _bump_backend_epoch()


def ensure_world(spec, init_timeout=None):
    """Join (or re-join) the process world described by ``spec``.

    Blocks until all ``spec.num_processes`` members arrive at the
    coordinator (jax.distributed's startup barrier) or the timeout
    elapses, in which case :class:`WorldBroken` is raised and the caller
    should re-poll the master for a fresher epoch.
    """
    global _active_spec
    if _active_spec == spec:
        return
    if _active_spec is not None:
        leave_world()

    import jax

    _configure_platform()
    # persistent compile cache (EDL_COMPILE_CACHE_DIR): re-formed worlds
    # drop every backend, so each world's first compile of an
    # already-seen step otherwise pays full XLA compile again; the
    # disk cache is keyed on the HLO and survives both re-forms and
    # process relaunches (docs/compile_plane.md)
    from elasticdl_tpu.parallel.compile_plane import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    if init_timeout is None:
        # short by design: members only enter the barrier after the
        # master's two-phase confirm (everyone alive and polling), so a
        # healthy formation completes in well under a second. A long
        # timeout only prolongs the stale-barrier case — a member that
        # took a ready spec just before the epoch bumped again — which
        # must fail fast (WorldBroken -> re-poll) *before* the master's
        # confirm window fences the silent process.
        init_timeout = world_init_timeout()
    logger.info(
        "joining world epoch=%d rank=%d/%d coordinator=%s",
        spec.epoch,
        spec.process_id,
        spec.num_processes,
        spec.coordinator,
    )
    # short-ish failure detection and shutdown barrier: a dead member
    # otherwise stalls every survivor's graceful leave for the default
    # 100 s heartbeat + 300 s shutdown windows
    heartbeat = int(os.environ.get("EDL_HEARTBEAT_TIMEOUT", "30"))
    shutdown_timeout = int(os.environ.get("EDL_SHUTDOWN_TIMEOUT", "30"))
    import time as _time

    t0 = _time.time()
    try:
        jax.distributed.initialize(
            spec.coordinator,
            num_processes=spec.num_processes,
            process_id=spec.process_id,
            initialization_timeout=init_timeout,
            heartbeat_timeout_seconds=heartbeat,
            shutdown_timeout_seconds=shutdown_timeout,
        )
        logger.info(
            "world epoch=%d formed in %.1fs",
            spec.epoch,
            _time.time() - t0,
        )
    except Exception as e:
        # failed mid-handshake (peer missing, stale epoch): leave cleanly
        # so the next attempt starts from scratch
        try:
            jax.distributed.shutdown()
        except Exception:
            logger.debug(
                "shutdown during failed world-form also failed "
                "(backends are cleared next anyway)",
                exc_info=True,
            )
        _clear_backends()
        raise WorldBroken(
            "could not form world epoch %d (%s)" % (spec.epoch, e)
        ) from e
    _active_spec = spec


def leave_world():
    """Leave the current world and invalidate all device handles."""
    global _active_spec
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        logger.warning("jax.distributed.shutdown failed", exc_info=True)
    _clear_backends()
    _active_spec = None
