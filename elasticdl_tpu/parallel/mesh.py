"""Device-mesh construction for elastic SPMD training.

The reference scales by adding/removing worker *pods* whose gradients meet
at a PS/master over gRPC (SURVEY.md §2.3). The TPU-native equivalent keeps
parameters and gradients in device HBM and lets XLA insert collectives over
ICI; the "cluster" is a ``jax.sharding.Mesh``. Elasticity = rebuilding the
mesh over the currently-usable device set and re-placing state (see
parallel/trainer.py); the task dispatcher above is unchanged.

Axis convention (the seam where tp/sp/ep land without touching the elastic
scheduler, SURVEY.md §5.7):

- ``data``  — data parallelism (gradient psum rides ICI)
- ``model`` — tensor parallelism for large layers
- ``seq``   — sequence/context parallelism (ring attention)
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def create_mesh(mesh_shape=None, axis_names=None, devices=None):
    """Build a Mesh.

    ``mesh_shape``: dict {axis_name: size} or None for all devices on one
    ``data`` axis. Sizes must multiply to the device count used; pass
    ``devices`` to build a mesh over a subset (elastic shrink).
    """
    if devices is None:
        devices = jax.devices()
    if mesh_shape is None:
        mesh_shape = {a: 1 for a in (axis_names or ())} or {
            "data": len(devices)
        }
        if axis_names:
            mesh_shape[axis_names[0]] = len(devices)
    if axis_names is None:
        axis_names = tuple(mesh_shape.keys())
    if set(axis_names) != set(mesh_shape):
        raise ValueError(
            "axis_names %s do not match mesh_shape keys %s"
            % (axis_names, tuple(mesh_shape))
        )
    sizes = tuple(mesh_shape[a] for a in axis_names)
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(
            "mesh needs %d devices, only %d available" % (n, len(devices))
        )
    dev_array = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(dev_array, axis_names)


def replicated(mesh):
    """Sharding for state replicated across the whole mesh."""
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh, axis="data"):
    """Sharding for a batch split on its leading dim over ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))


def shard_batch(mesh, batch, axis="data"):
    """Place a host batch onto the mesh, leading dim split over ``axis``.

    The axis size must divide the global batch size; the elastic trainer
    sizes global batches as (per-chip batch) x (axis size) so this holds
    across resizes.
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch
    )


def replicate(mesh, tree):
    """Place a pytree fully-replicated onto the mesh."""
    sharding = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), tree
    )
