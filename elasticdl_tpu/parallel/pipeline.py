"""Pipeline parallelism: layer stages over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.2: absent).
This is the TPU-native form: the network's layers are grouped into S
stages, stage s's parameters live only on the devices at ``pipe`` index
s, and microbatches flow through the stage ring with
``lax.ppermute`` — the GPipe schedule expressed as a ``lax.scan`` over
S + M - 1 ticks inside ``shard_map``. XLA overlaps each tick's
stage compute with the activation rotation (async collectives over
ICI), and reverse-mode AD through scan + ppermute yields the matching
1F1B-shaped backward without any hand-written schedule.

Composes with the other axes on one mesh: ``data`` shards the batch,
``pipe`` shards depth. Stage parameters arrive *stacked* on a leading
stage dimension (leaf shape (S, ...) sharded P('pipe', ...)), the layout
:func:`stack_stage_params` builds and
:func:`elasticdl_tpu.parallel.trainer.AllReduceTrainer` can place via
param_specs.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.ring_attention import shard_map


def stack_stage_params(per_stage_params):
    """[params_stage0, ...] -> one pytree with a leading (S,) stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params
    )


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name):
    """Run the stage ring over microbatches; call inside shard_map.

    - ``stage_fn(params, x) -> y``: one stage's computation; every stage
      must map the same activation shape to itself (classic pipeline
      constraint — embed/head layers live outside the ring).
    - ``stage_params``: this device's slice of the stacked stage params
      (leading dim 1, squeezed internally).
    - ``microbatches``: (M, mb, ...) activations, replicated along
      ``axis_name`` (every stage sees the input stream; only stage 0
      consumes it).

    Returns (M, mb, ...) outputs, valid on the LAST stage (callers take
    index S-1; the shard_map wrapper below does).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(
        lambda x: jnp.squeeze(x, axis=0), stage_params
    )
    m = microbatches.shape[0]
    mb_shape = microbatches.shape[1:]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        held, outputs = carry
        # stage 0 ingests microbatch t (if any remain); others keep the
        # activation that just rotated in
        feed = jnp.where(
            t < m,
            jax.lax.dynamic_index_in_dim(
                microbatches, jnp.minimum(t, m - 1), keepdims=False
            ),
            jnp.zeros(mb_shape, microbatches.dtype),
        )
        x = jnp.where(stage == 0, feed, held)
        y = stage_fn(params, x)
        # the last stage's result for microbatch (t - (S-1)) is ready
        out_idx = t - (n_stages - 1)
        outputs = jnp.where(
            (out_idx >= 0) & (out_idx < m),
            jax.lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_idx, 0, m - 1), axis=0
            ),
            outputs,
        )
        held_next = jax.lax.ppermute(y, axis_name, perm)
        return (held_next, outputs), None

    held0 = jnp.zeros(mb_shape, microbatches.dtype)
    outputs0 = jnp.zeros((m,) + mb_shape, microbatches.dtype)
    (_, outputs), _ = jax.lax.scan(
        tick,
        (held0, outputs0),
        jnp.arange(m + n_stages - 1),
    )
    return outputs


def make_pipeline_fn(mesh, stage_fn, pipe_axis="pipe", batch_axis=None):
    """Global-array wrapper: ``(stacked_params, microbatches) -> out``.

    ``stacked_params`` leaves are (S, ...) sharded over ``pipe_axis``;
    ``microbatches`` is (M, mb, ...) (optionally batch-sharded over
    ``batch_axis`` on dim 1 for dp x pp). Output matches microbatches'
    shape/sharding: the last stage's results, broadcast over the pipe
    axis so downstream (loss) code sees ordinary replicated activations.
    """

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(pipe_axis),
            P(None, batch_axis),
        ),
        out_specs=P(None, batch_axis),
        check_rep=False,
    )
    def _pipe(stacked_params, microbatches):
        out = pipeline_apply(
            stage_fn, stacked_params, microbatches, pipe_axis
        )
        # broadcast the last stage's outputs to every pipe rank so the
        # result is replicated along the pipe axis
        n_stages = jax.lax.psum(1, pipe_axis)
        stage = jax.lax.axis_index(pipe_axis)
        mask = (stage == n_stages - 1).astype(out.dtype)
        return jax.lax.psum(out * mask, pipe_axis)

    return _pipe


def stage_param_sharding(mesh, pipe_axis="pipe"):
    """NamedSharding for stacked stage parameters."""
    return NamedSharding(mesh, P(pipe_axis))


def collective_pipeline_apply(
    stage_fn, local_stage_params, x_local, pipe_axis, microbatches=0
):
    """Pipeline ring over per-device batch rows INSIDE an enclosing
    shard_map — the elastic weighted step's form of pipeline
    parallelism, the same raw-collective recipe as
    nn/hbm_embedding.py's ``collective=True`` lookups (a nested
    shard_map is impossible there).

    - ``local_stage_params``: this device's slice of the stacked stage
      params — leading dim 1 (the pipe axis size must equal the stage
      count).
    - ``x_local``: (b_loc, ...) THIS device's activation rows (each
      device of a data group holds different rows).
    - Returns (b_loc, ...): the ring outputs for exactly this device's
      rows.

    Data flow: all_gather the data group's rows over ``pipe_axis`` (so
    stage 0 can ingest the whole group's stream), microbatch, run the
    ring, psum-broadcast the last stage's outputs back over the pipe
    axis, slice this device's rows back out. Gradient flow is exact:
    the all_gather's transpose routes activation gradients back to each
    row's source device; the ppermute transposes inside the ring's
    backward deliver each stage's parameter gradients to that stage's
    devices (the step then psums them over the remaining axes).
    """
    n_stages = jax.lax.psum(1, pipe_axis)
    stage = jax.lax.axis_index(pipe_axis)
    b_loc = x_local.shape[0]
    group = jax.lax.all_gather(x_local, pipe_axis, tiled=True)
    rows = group.shape[0]
    m = microbatches or n_stages
    padded = -(-rows // m) * m
    if padded != rows:
        group = jnp.concatenate(
            [
                group,
                jnp.broadcast_to(
                    group[-1:], (padded - rows,) + group.shape[1:]
                ),
            ]
        )
    micro = jnp.reshape(group, (m, padded // m) + group.shape[1:])
    out = pipeline_apply(stage_fn, local_stage_params, micro, pipe_axis)
    # only the last stage's outputs are the ring's result; broadcast
    # them to every pipe rank so each can slice its own rows
    mask = (stage == n_stages - 1).astype(out.dtype)
    out = jax.lax.psum(out * mask, pipe_axis)
    flat = jnp.reshape(out, (padded,) + out.shape[2:])[:rows]
    return jax.lax.dynamic_slice_in_dim(flat, stage * b_loc, b_loc, 0)


class PipelinedStack(nn.Module):
    """Flax module running a stage template through the pipe ring.

    The job-path integration of :func:`pipeline_apply`: drop this into a
    model where a sequential stack of identical-shape layers would sit
    (transformer blocks — embed/head stay outside the ring), declare its
    ``stages`` parameter subtree as ``{"**": P("pipe")}`` in the zoo's
    ``param_shardings``, and the ALLREDUCE trainers place each stage's
    parameters only on that stage's devices.

    - ``stage_template``: an UNBOUND module whose ``__call__(x)`` maps an
      activation to the same shape (the classic pipeline constraint).
    - ``n_stages``: ring length; must equal the mesh's ``pipe`` axis size.
    - ``microbatches``: how many microbatches the incoming batch splits
      into (0 -> ``n_stages``; more microbatches shrink the bubble,
      S/(S+M-1) of ticks are ramp).
    - ``mesh=None``: degenerate single-device form — runs the stages
      sequentially (used for init shape-tracing and CPU smoke tests).
    - ``collective=True``: the module is being applied INSIDE an
      enclosing shard_map whose mesh has a ``pipe`` axis (the elastic
      weighted step). The stacked param arrives as this device's local
      (1, ...) stage slice, and the ring runs via raw collectives
      (:func:`collective_pipeline_apply`) — ``mesh`` stays None. Init
      still traces the sequential form and creates the full (S, ...)
      stacked parameters.

    Parameters are created by initializing the template once per stage
    and stacking each leaf on a leading (S,) dim — a single flax param
    whose value is the stacked subtree, so checkpoints/optimizers see
    ordinary (S, ...) leaves.
    """

    stage_template: object
    n_stages: int
    mesh: object = None
    pipe_axis: str = "pipe"
    microbatches: int = 0
    collective: bool = False

    @nn.compact
    def __call__(self, x):
        m = self.microbatches or self.n_stages

        def init_fn(rng):
            rngs = jax.random.split(rng, self.n_stages)
            per = [
                self.stage_template.init(r, x[:1])["params"]
                for r in rngs
            ]
            return stack_stage_params(per)

        if self.collective:
            # self.variable, not self.param: flax shape-validates params
            # against their initializer at apply time, but in collective
            # mode the apply-time value is this device's (1, ...) LOCAL
            # stage slice of the declared (S, ...) stacked subtree (the
            # same recipe as nn/hbm_embedding.py's collective table)
            stacked = self.variable(
                "params",
                "stages",
                lambda: init_fn(self.make_rng("params")),
            ).value
        else:
            stacked = self.param("stages", init_fn)

        def stage_fn(params, act):
            return self.stage_template.apply({"params": params}, act)

        if self.collective and not self.is_initializing():
            return collective_pipeline_apply(
                stage_fn,
                stacked,
                x,
                self.pipe_axis,
                microbatches=self.microbatches,
            )
        if (
            self.is_initializing()
            or self.mesh is None
            or self.pipe_axis not in getattr(self.mesh, "axis_names", ())
        ):
            # sequential reference form: init tracing (single example,
            # no microbatching possible) and pipe-less meshes
            y = x
            for s in range(self.n_stages):
                p = jax.tree_util.tree_map(
                    lambda a, s=s: a[s], stacked
                )
                y = stage_fn(p, y)
            return y
        batch_axis = (
            "data" if "data" in self.mesh.axis_names else None
        )
        # pad ragged batches (eval tails) up to a whole number of
        # microbatch rows per data shard, slice the padding back off
        chunk = m * (
            self.mesh.shape[batch_axis] if batch_axis else 1
        )
        b = x.shape[0]
        padded = -(-b // chunk) * chunk
        if padded != b:
            x = jnp.concatenate(
                [x, jnp.broadcast_to(x[-1:], (padded - b,) + x.shape[1:])]
            )
        micro = jnp.reshape(x, (m, padded // m) + x.shape[1:])
        out = make_pipeline_fn(
            self.mesh,
            stage_fn,
            pipe_axis=self.pipe_axis,
            batch_axis=batch_axis,
        )(stacked, micro)
        out = jnp.reshape(out, (padded,) + out.shape[2:])
        return out[:b]


def reference_pipeline(stage_fn, per_stage_params, microbatches):
    """Sequential semantics the ring must match (tests)."""
    outs = []
    for x in np.asarray(microbatches):
        y = jnp.asarray(x)
        for params in per_stage_params:
            y = stage_fn(params, y)
        outs.append(y)
    return jnp.stack(outs)
