"""Ring attention: exact attention over sequence-sharded inputs.

Long-context training shards the sequence axis across devices (the mesh's
``seq`` axis). Each device keeps its Q shard resident and K/V shards rotate
around the ring via ``ppermute`` over ICI; partial attention outputs merge
with the online-softmax (flash) recurrence, so the full (L, L) score matrix
never materializes and memory stays O(L_local).

This is the blockwise ring attention of Liu et al. (Ring Attention with
Blockwise Transformers, 2023), built with shard_map + XLA collectives —
the per-device block kernel lowers to the MXU, and the K/V rotation
overlaps with compute via XLA's async collective scheduling.

No counterpart exists in the reference (no attention models, SURVEY.md
§5.7); this subsystem is the framework's long-context scaling axis.
"""

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma in jax 0.8
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, **kwargs):
    if "check_rep" in kwargs:
        kwargs[_CHECK_KW] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def _block_attend(q, k, v, bias=None):
    """Scores + flash statistics for one (Q_block, KV_block) pair.

    q: (B, Lq, H, D), k/v: (B, Lk, H, D). Returns (out_unnorm, row_max,
    row_sum) with out_unnorm = exp(s - row_max) @ v.
    """
    scale = q.shape[-1] ** -0.5
    # online-softmax statistics must form in f32 even for bf16 q/k/v —
    # bf16 s/m/p/l loses precision the f32 accumulators can't recover
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    m = jnp.max(s, axis=-1)  # (B, H, Lq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B, H, Lq)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    )
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two flash partials (associative online-softmax combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[
        ..., None
    ]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _causal_bias(q_offset, k_offset, lq, lk, dtype):
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
    k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
    return jnp.where(q_pos >= k_pos, 0.0, jnp.finfo(dtype).min)


def ring_attention(q, k, v, axis_name, causal=False):
    """Exact attention with K/V rotating around ``axis_name``.

    Call *inside* shard_map with q/k/v already sequence-sharded:
    q, k, v: (B, L_local, H, D). Returns (B, L_local, H, D).
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    l_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        o, m, l, kk, vv = carry
        # the K/V block now held came from device (my_idx - step) % n
        src = (my_idx - step) % n
        if causal:
            bias = _causal_bias(
                my_idx * l_local,
                src * l_local,
                l_local,
                kk.shape[1],
                q.dtype,
            )[None, None]
        else:
            bias = None
        bo, bm, bl = _block_attend(q, kk, vv, bias)
        o, m, l = _merge(o, m, l, bo, bm, bl)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return o, m, l, kk, vv

    b, _, h, d = q.shape
    o0 = jnp.zeros((b, l_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, l_local), jnp.finfo(jnp.float32).min, jnp.float32)
    l0 = jnp.zeros((b, h, l_local), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(mesh, seq_axis="seq", causal=False):
    """shard_map-wrapped ring attention over ``mesh[seq_axis]``.

    Inputs/outputs are global (B, L, H, D) arrays sharded on L. The batch
    dim additionally shards over ``data`` and the head dim over ``model``
    when those axes exist in the mesh, so dp x tp replicas each attend
    over their own batch/head slice — the ring only rotates K/V along
    ``seq_axis``.
    """
    axes = set(mesh.axis_names)
    batch_axis = "data" if "data" in axes and "data" != seq_axis else None
    head_axis = "model" if "model" in axes and "model" != seq_axis else None
    spec = P(batch_axis, seq_axis, head_axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    def _ring(q, k, v):
        return ring_attention(q, k, v, seq_axis, causal=causal)

    return _ring


def reference_attention(q, k, v, causal=False):
    """Plain XLA attention (for tests and single-device fallback)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        s = s + _causal_bias(0, 0, lq, lk, q.dtype)[None, None]
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
