"""Ring attention: exact attention over sequence-sharded inputs.

Long-context training shards the sequence axis across devices (the mesh's
``seq`` axis). Each device keeps its Q shard resident and K/V shards rotate
around the ring via ``ppermute`` over ICI; partial attention outputs merge
with the online-softmax (flash) recurrence, so the full (L, L) score matrix
never materializes and memory stays O(L_local).

This is the blockwise ring attention of Liu et al. (Ring Attention with
Blockwise Transformers, 2023), built with shard_map + XLA collectives —
the per-device block kernel lowers to the MXU, and the K/V rotation
overlaps with compute via XLA's async collective scheduling.

No counterpart exists in the reference (no attention models, SURVEY.md
§5.7); this subsystem is the framework's long-context scaling axis.
"""

import functools
import inspect

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma in jax 0.8
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)


def shard_map(f, **kwargs):
    if "check_rep" in kwargs:
        kwargs[_CHECK_KW] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def _block_attend(q, k, v, bias=None):
    """Scores + flash statistics for one (Q_block, KV_block) pair.

    q: (B, Lq, H, D), k/v: (B, Lk, H, D). Returns (out_unnorm, row_max,
    row_sum) with out_unnorm = exp(s - row_max) @ v.
    """
    scale = q.shape[-1] ** -0.5
    # online-softmax statistics must form in f32 even for bf16 q/k/v —
    # bf16 s/m/p/l loses precision the f32 accumulators can't recover
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    m = jnp.max(s, axis=-1)  # (B, H, Lq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B, H, Lq)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    )
    return o, m, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two flash partials (associative online-softmax combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[
        ..., None
    ]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _causal_bias(q_offset, k_offset, lq, lk, dtype):
    q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
    k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
    return jnp.where(q_pos >= k_pos, 0.0, jnp.finfo(dtype).min)


def ring_attention(q, k, v, axis_name, causal=False):
    """Exact attention with K/V rotating around ``axis_name``.

    Call *inside* shard_map with q/k/v already sequence-sharded:
    q, k, v: (B, L_local, H, D). Returns (B, L_local, H, D).
    """
    n = jax.lax.psum(1, axis_name)
    # axis_index only under causal: a dead axis_index lowers to a
    # partition_id instruction with no data dependence on the manual
    # region's operands, which XLA hoists out of it — and the SPMD
    # partitioner rejects PartitionId outside manual sharding
    # ("PartitionId instruction is not supported for SPMD
    # partitioning"). The non-causal ring needs no rank at all.
    my_idx = jax.lax.axis_index(axis_name) if causal else None
    l_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        o, m, l, kk, vv = carry
        if causal:
            # the K/V block now held came from device (my_idx - step) % n
            src = (my_idx - step) % n
            bias = _causal_bias(
                my_idx * l_local,
                src * l_local,
                l_local,
                kk.shape[1],
                q.dtype,
            )[None, None]
        else:
            bias = None
        bo, bm, bl = _block_attend(q, kk, vv, bias)
        o, m, l = _merge(o, m, l, bo, bm, bl)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return o, m, l, kk, vv

    b, _, h, d = q.shape
    o0 = jnp.zeros((b, l_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, l_local), jnp.finfo(jnp.float32).min, jnp.float32)
    l0 = jnp.zeros((b, h, l_local), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Fused-kernel ring: per-block Pallas flash attention with (out, lse)
# merging, and a custom VJP that re-rotates K/V around the ring in the
# backward — so training memory stays O(L_local x block) per device (the
# Ring Attention recipe), instead of saving every rotated K/V block as a
# scan residual.
# ---------------------------------------------------------------------------


def _merge_normalized(o1, lse1, o2, lse2):
    """Merge two *normalized* partial attentions by their logsumexps."""
    lse = jnp.logaddexp(lse1, lse2)
    # both sides empty (fully masked so far): weights 0, not NaN
    finite = jnp.isfinite(lse)
    w1 = jnp.where(finite, jnp.exp(lse1 - jnp.where(finite, lse, 0.0)), 0.0)
    w2 = jnp.where(finite, jnp.exp(lse2 - jnp.where(finite, lse, 0.0)), 0.0)
    o = (
        o1 * w1.transpose(0, 2, 1)[..., None]
        + o2 * w2.transpose(0, 2, 1)[..., None]
    )
    return o, lse


def _block_cases(src, my_idx, causal, diag_fn, full_fn, skip_fn):
    """Ring blocks see equal-size shards, so causal masking is all-or-
    nothing per block: diagonal (src == my), fully visible (src < my), or
    fully masked (src > my)."""
    if not causal:
        return full_fn(None)
    return jax.lax.cond(
        src == my_idx,
        diag_fn,
        lambda _: jax.lax.cond(src < my_idx, full_fn, skip_fn, None),
        None,
    )


def ring_flash_attention(
    q, k, v, axis_name, causal=False, block_q=None, block_k=None
):
    """Ring attention whose per-block compute is the fused Pallas kernel.

    Call inside shard_map with q/k/v sequence-sharded (B, L_local, H, D).
    Forward carries (normalized out, lse) and merges blocks by logsumexp;
    backward re-rotates K/V (and their gradient accumulators) around the
    ring, running the blockwise flash backward against the *global* lse —
    so neither pass materializes more than one K/V block beyond the
    residents, and no (L, L) score matrix exists anywhere.
    """
    from elasticdl_tpu.ops.flash_attention import auto_blocks

    # resolve here (not per inner call): the custom_vjp's nondiff args
    # must be concrete and identical across the fwd/bwd ring loops
    block_q, block_k = auto_blocks(
        q.shape[1], k.shape[1], block_q, block_k
    )
    return _ring_flash(q, k, v, axis_name, causal, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k):
    out, _ = _ring_flash_fwd_loop(
        q, k, v, axis_name, causal, block_q, block_k
    )
    return out


def _ring_flash_fwd_loop(q, k, v, axis_name, causal, block_q, block_k):
    from elasticdl_tpu.ops.flash_attention import flash_attention_with_lse

    n = jax.lax.psum(1, axis_name)
    # rank only under causal — see ring_attention: a dead axis_index
    # becomes a hoisted PartitionId the SPMD partitioner rejects
    my_idx = jax.lax.axis_index(axis_name) if causal else None
    b, l_local, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(kk, vv, block_causal):
        o, lse = flash_attention_with_lse(
            q, kk, vv, block_causal, block_q, block_k
        )
        return o.astype(jnp.float32), lse

    def body(step, carry):
        o, lse, kk, vv = carry
        src = (my_idx - step) % n if causal else None
        o_b, lse_b = _block_cases(
            src,
            my_idx,
            causal,
            diag_fn=lambda _: attend(kk, vv, True),
            full_fn=lambda _: attend(kk, vv, False),
            skip_fn=lambda _: (
                jnp.zeros((b, l_local, h, d), jnp.float32),
                jnp.full((b, h, l_local), -jnp.inf, jnp.float32),
            ),
        )
        o, lse = _merge_normalized(o, lse, o_b, lse_b)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return o, lse, kk, vv

    o0 = jnp.zeros((b, l_local, h, d), jnp.float32)
    lse0 = jnp.full((b, h, l_local), -jnp.inf, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, n, body, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, block_q, block_k):
    out, lse = _ring_flash_fwd_loop(
        q, k, v, axis_name, causal, block_q, block_k
    )
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(
    axis_name, causal, block_q, block_k, residuals, g
):
    from elasticdl_tpu.ops.flash_attention import _flash_bwd, _use_interpret

    q, k, v, out, lse = residuals
    n = jax.lax.psum(1, axis_name)
    # rank only under causal — see ring_attention: a dead axis_index
    # becomes a hoisted PartitionId the SPMD partitioner rejects
    my_idx = jax.lax.axis_index(axis_name) if causal else None
    perm = [(i, (i + 1) % n) for i in range(n)]
    interpret = _use_interpret()

    def block_bwd(kk, vv, block_causal):
        return _flash_bwd(
            q,
            kk,
            vv,
            out,
            lse,
            g,
            block_causal,
            block_q,
            block_k,
            interpret,
        )

    def body(step, carry):
        dq, dkk, dvv, kk, vv = carry
        src = (my_idx - step) % n if causal else None
        dq_b, dk_b, dv_b = _block_cases(
            src,
            my_idx,
            causal,
            diag_fn=lambda _: block_bwd(kk, vv, True),
            full_fn=lambda _: block_bwd(kk, vv, False),
            skip_fn=lambda _: (
                jnp.zeros_like(q),
                jnp.zeros_like(k),
                jnp.zeros_like(v),
            ),
        )
        dq = dq + dq_b.astype(jnp.float32)
        dkk = dkk + dk_b.astype(jnp.float32)
        dvv = dvv + dv_b.astype(jnp.float32)
        # rotate the gradient accumulators WITH their K/V blocks: after n
        # steps each block (and its accumulated grad) is home again
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        dkk = jax.lax.ppermute(dkk, axis_name, perm)
        dvv = jax.lax.ppermute(dvv, axis_name, perm)
        return dq, dkk, dvv, kk, vv

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq, dk, dv, _, _ = jax.lax.fori_loop(
        0, n, body, (dq0, dk0, dv0, k, v)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def make_ring_attention(
    mesh, seq_axis="seq", causal=False, use_flash=True, block_q=128,
    block_k=128,
):
    """shard_map-wrapped ring attention over ``mesh[seq_axis]``.

    Inputs/outputs are global (B, L, H, D) arrays sharded on L. The batch
    dim additionally shards over ``data`` and the head dim over ``model``
    when those axes exist in the mesh, so dp x tp replicas each attend
    over their own batch/head slice — the ring only rotates K/V along
    ``seq_axis``.

    ``use_flash`` (default) runs the fused Pallas kernel per block with
    the blockwise ring backward; the XLA fallback materializes per-block
    scores (O(L_local x L_block) memory) and differentiates through the
    scan.
    """
    axes = set(mesh.axis_names)
    batch_axis = "data" if "data" in axes and "data" != seq_axis else None
    head_axis = "model" if "model" in axes and "model" != seq_axis else None
    spec = P(batch_axis, seq_axis, head_axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    def _ring(q, k, v):
        from elasticdl_tpu.ops.flash_attention import divisible

        if use_flash and divisible(
            q.shape[1], k.shape[1], block_q, block_k
        ):
            return ring_flash_attention(
                q,
                k,
                v,
                seq_axis,
                causal=causal,
                block_q=block_q,
                block_k=block_k,
            )
        # shard lengths the kernel can't tile keep the XLA path
        return ring_attention(q, k, v, seq_axis, causal=causal)

    return _ring


def reference_attention(q, k, v, causal=False):
    """Plain XLA attention (for tests and single-device fallback)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        s = s + _causal_bias(0, 0, lq, lk, q.dtype)[None, None]
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
