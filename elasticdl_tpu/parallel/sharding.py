"""Parameter/batch sharding rules for dp x tp x sp meshes.

The scaling recipe (How to Scale Your Model): pick a mesh, annotate
parameter and activation shardings with PartitionSpecs, and let XLA insert
the collectives. This module maps transformer parameter pytrees onto the
framework's mesh axes:

- ``data``  — batch dim of activations (gradient psum over ICI)
- ``model`` — tensor parallelism: attention heads + MLP hidden
- ``seq``   — sequence parallelism: activation L dim (ring attention)

Rules are name-pattern based over the flattened parameter paths (the same
path names used by the wire codec and checkpoints), so any flax model
whose large layers follow the naming conventions gets tp for free; unknown
parameters replicate.
"""

import re

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

# (path regex, spec builder) — first match wins. Specs reference the
# ``model`` axis; axes absent from the mesh are dropped automatically.
_TP_RULES = (
    # attention projections: qkv kernels (D, H, Dh) shard heads;
    # out projection (H, Dh, D) shards heads
    (
        re.compile(r"(.*/)?(query|key|value)/kernel$"),
        P(None, "model", None),
    ),
    (re.compile(r"(.*/)?out/kernel$"), P("model", None, None)),
    # MLP: up-projection shards hidden out, down-projection shards hidden in
    (re.compile(r"(.*/)?mlp_up/kernel$"), P(None, "model")),
    (re.compile(r"(.*/)?mlp_down/kernel$"), P("model", None)),
    (re.compile(r"(.*/)?mlp_up/bias$"), P("model")),
    # token embedding / LM head shard the embedding table on vocab
    (re.compile(r"(.*/)?embed/embedding$"), P("model", None)),
)


# The same rules as a nested suffix-spec dict — the shape zoo
# ``param_shardings`` hooks emit and the elastic trainer's
# collect_sharded_paths/spec_path_matches machinery consumes (a leaf
# whose path ENDS WITH a key path gets the spec; optimizer slot trees
# co-shard automatically). This is the promotion of the name-pattern TP
# rules into the elastic world: a zoo returns ``tp_param_specs()`` and
# ElasticDPTrainer places dense parameters via NamedSharding over the
# 2D data x model mesh instead of replicating them everywhere
# (docs/distributed.md).
_TP_SUFFIX_SPECS = {
    "query": {"kernel": P(None, "model", None)},
    "key": {"kernel": P(None, "model", None)},
    "value": {"kernel": P(None, "model", None)},
    "out": {"kernel": P("model", None, None)},
    "mlp_up": {"kernel": P(None, "model"), "bias": P("model")},
    "mlp_down": {"kernel": P("model", None)},
    "embed": {"embedding": P("model", None)},
}


def tp_param_specs():
    """Nested {path segment: ... PartitionSpec} dict of the TP rules.

    Returns a fresh copy each call so a caller merging extra specs in
    cannot mutate the module-level table."""
    return {k: dict(v) for k, v in _TP_SUFFIX_SPECS.items()}


def tp_degree_candidates(model_dim_sizes, max_degree=None):
    """The tp degrees a model admits: every degree that divides EVERY
    model-sharded dimension (attention heads, MLP hidden, vocab...),
    ascending, 1 always included. The layout solver intersects these
    with the divisors of the world size, so a solver-chosen degree can
    never produce a shard the mesh rejects. Pure host math — safe on
    the establish path and the speculative compiler's daemon thread."""
    dims = sorted({int(d) for d in model_dim_sizes if int(d) > 0})
    if not dims:
        return (1,)
    limit = dims[0]
    if max_degree:
        limit = min(limit, int(max_degree))
    return tuple(
        deg
        for deg in range(1, limit + 1)
        if all(d % deg == 0 for d in dims)
    )


def _drop_missing_axes(spec, mesh):
    axes = set(mesh.axis_names)
    return P(*(a if a in axes else None for a in spec))


def param_spec(path_name, mesh):
    for pattern, spec in _TP_RULES:
        if pattern.match(path_name):
            return _drop_missing_axes(spec, mesh)
    return P()


def shard_params(mesh, params):
    """Place a parameter pytree per the tp rules; returns sharded pytree."""
    from elasticdl_tpu.common.tensor import _join_path

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = [
        NamedSharding(mesh, param_spec(_join_path(path), mesh))
        for path, _ in flat
    ]
    # one batched transfer instead of a per-leaf Python loop
    placed = jax.device_put([leaf for _, leaf in flat], shardings)
    return jax.tree_util.tree_unflatten(treedef, placed)


def batch_spec(mesh, seq_sharded=False):
    """Activation spec: batch on ``data``, optionally L on ``seq``."""
    axes = set(mesh.axis_names)
    data = "data" if "data" in axes else None
    seq = "seq" if (seq_sharded and "seq" in axes) else None
    return P(data, seq)


def shard_batch_dp_sp(mesh, batch, seq_sharded=False):
    spec = batch_spec(mesh, seq_sharded)
    sharding = NamedSharding(mesh, spec)

    def place(x):
        target = (
            NamedSharding(mesh, P(*list(spec)[: x.ndim]))
            if x.ndim < len(spec)
            else sharding
        )
        return jax.device_put(x, target)

    return jax.tree_util.tree_map(place, batch)
