"""Elastic on-device data-parallel trainer (the ALLREDUCE strategy).

Replaces the reference's dense-gradient RPC plane (GetModel/ReportGradient
full-tensor round trips, SURVEY.md §3.3) with a single jitted train step
over a ``jax.sharding.Mesh``: parameters live replicated in HBM, the global
batch is split over the ``data`` axis, and XLA inserts the gradient
reduction over ICI — the ``grads_to_wait`` barrier *is* the collective.

Elasticity: ``resize(devices)`` rebuilds the mesh over the surviving/new
device set and re-places the train state. Compiled steps are cached per
(mesh shape, batch shape) so repeated membership changes between the same
world sizes pay compilation once (SURVEY.md §7.3 amortization note). The
task dispatcher above is untouched: a resize looks like "some workers'
tasks were recovered" plus a barrier.
"""

import jax
import numpy as np
from jax.sharding import NamedSharding

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.mesh import (
    create_mesh,
    replicate,
    replicated,
    shard_batch,
)
from elasticdl_tpu.training.step import TrainState, make_train_step


class AllReduceTrainer:
    def __init__(
        self,
        module,
        loss_fn,
        optimizer,
        devices=None,
        batch_axis="data",
        seed=0,
        mesh=None,
        param_specs=None,
        accum_steps=1,
        precision=None,
        remat=False,
    ):
        """``param_specs``: optional nested dict mirroring (a prefix of)
        the params tree whose leaves are PartitionSpecs — parameters it
        names shard over the mesh instead of replicating (HBM embedding
        tables); their optimizer slots co-shard by tree-path suffix.
        ``accum_steps``/``precision`` forward to
        :func:`training.step.make_train_step` (gradient accumulation and
        the mixed-precision policy)."""
        self._module = module
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._batch_axis = batch_axis
        self._seed = seed
        self._param_specs = param_specs
        self._sharded_paths = {}
        # the persistent compile cache covers this plane too: a
        # restarted local job re-jits the identical step HLO, which the
        # disk cache (EDL_COMPILE_CACHE_DIR) satisfies without an XLA
        # compile (docs/compile_plane.md)
        from elasticdl_tpu.parallel.compile_plane import (
            enable_persistent_cache,
        )

        # probe_backend: this single-process trainer touches the backend
        # at construction anyway (create_mesh below), so asking it
        # directly catches an accelerator-less box jax lands on CPU
        # implicitly — where a cache-reloaded donated executable would
        # crash (see enable_persistent_cache)
        enable_persistent_cache(probe_backend=True)
        self._step_fn = make_train_step(
            module,
            loss_fn,
            optimizer,
            accum_steps=accum_steps,
            precision=precision,
            remat=remat,
        )
        self._mesh = mesh if mesh is not None else create_mesh(devices=devices)
        self._ts = None
        self._host_step = 0

    @property
    def mesh(self):
        return self._mesh

    @property
    def num_devices(self):
        return self._mesh.devices.size

    @property
    def train_state(self):
        return self._ts

    @property
    def version(self):
        return int(self._ts.version) if self._ts is not None else -1

    def _collect_sharded_paths(self):
        """Flatten param_specs into {path tuple: NamedSharding}.

        ``"**"`` keys mark subtree specs (every leaf under the prefix) —
        see parallel/elastic.py collect_sharded_paths."""
        from elasticdl_tpu.parallel.elastic import collect_sharded_paths

        return {
            path: NamedSharding(self._mesh, spec)
            for path, spec in collect_sharded_paths(
                self._param_specs
            ).items()
        }

    @staticmethod
    def _key_names(key_path):
        from elasticdl_tpu.common.pytree import key_path_names

        return key_path_names(key_path)

    def _place(self, tree):
        """Place a host pytree: leaves whose tree path *ends with* a
        spec path shard, the rest replicates.

        Suffix matching places both the parameters themselves (path ==
        spec path) and their optimizer slots (optax moment trees nest the
        same sub-structure under mu/nu/...), without false positives on
        unrelated leaves that merely share a shape.
        """
        rep = replicated(self._mesh)
        specs = self._sharded_paths

        from elasticdl_tpu.parallel.elastic import spec_path_matches

        def put(key_path, x):
            names = self._key_names(key_path)
            for spec_path, sharding in specs.items():
                if spec_path_matches(spec_path, names):
                    return jax.device_put(x, sharding)
            return jax.device_put(x, rep)

        return jax.tree_util.tree_map_with_path(put, tree)

    def init_from_batch(self, global_batch):
        """Create + place train state from one example batch."""
        features = (
            global_batch[0]
            if isinstance(global_batch, tuple)
            else global_batch
        )
        # slice BEFORE the host transfer: init only needs one example's
        # shape, and np.asarray on the full leaf would D2H the whole
        # batch (a device leaf slices on device; a numpy leaf stays a
        # view either way)
        host_features = jax.tree_util.tree_map(
            lambda x: np.asarray(x[:1]), features
        )
        variables = init_variables(
            self._module, jax.random.PRNGKey(self._seed), host_features
        )
        params, state = split_variables(variables)
        ts = TrainState.create(params, state, self._optimizer)
        self._sharded_paths = self._collect_sharded_paths()
        self._ts = self._place(ts)
        return self._ts

    def load_state(self, ts):
        """Adopt an existing host/device train state (checkpoint restore)."""
        self._sharded_paths = self._collect_sharded_paths()
        self._ts = self._place(ts)

    def train_step(self, features, labels):
        """One global step. Batch leading dim must divide the data axis."""
        if self._ts is None:
            self.init_from_batch((features, labels))
        features = shard_batch(self._mesh, features, self._batch_axis)
        labels = shard_batch(self._mesh, labels, self._batch_axis)
        self._host_step += 1
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self._seed), self._host_step
        )
        with self._mesh:
            self._ts, loss = self._step_fn(self._ts, features, labels, rng)
        return loss

    def resize(self, devices):
        """Membership change: rebuild the mesh and re-place state.

        Survivor state is the source of truth (replaces the reference's
        re-push-from-workers PS re-init, ps/servicer.py:70-79). The
        re-placement is a direct ``device_put`` from the old placement to
        the new mesh's shardings — the runtime moves buffers
        device-to-device (ICI/DMA) where it can, instead of a forced
        full HBM -> host -> HBM round trip of every parameter.
        """
        from elasticdl_tpu.utils import profiling

        old_ts = self._ts
        self._mesh = create_mesh(devices=devices)
        logger.info(
            "membership epoch: mesh re-formed over %d devices",
            self.num_devices,
        )
        if old_ts is not None:
            self._sharded_paths = self._collect_sharded_paths()
            # the step fn object is reused across resizes, so stepping
            # again at a previously-seen device set hits jax's aval
            # cache (no retrace/recompile); only the state re-placement
            # below is per-resize work — annotated so it separates in
            # traces
            with profiling.annotate("allreduce/resize/replace"):
                self._ts = self._place(old_ts)

    def get_host_state(self):
        """Pull the train state to host memory (for checkpointing).

        Leaves come back as OWNED copies: ``np.asarray`` on a CPU
        backend returns a zero-copy view of the device buffer, and this
        trainer's step DONATES its state — a checkpoint thread reading
        such a view races the next step recycling the buffer. Sharded
        leaves gather through ``jax.device_get`` (assembling the
        addressable shards) before the same owned-copy floor."""

        def fetch(x):
            if hasattr(x, "addressable_shards"):
                x = jax.device_get(x)
            # np.array(copy=True): never a view of device memory
            return np.array(x, copy=True)

        return jax.tree_util.tree_map(fetch, self._ts)

    def save_sharded(self, directory):
        """Per-shard checkpoint: HBM-sharded parameters (embedding
        tables) write one file per device shard — no dense gather."""
        from elasticdl_tpu.common.sharded_checkpoint import save_sharded

        save_sharded(directory, self._ts, version=self.version)

    def restore_sharded(self, directory):
        """Restore a sharded checkpoint onto the current placement
        (state must be initialized first, e.g. via init_from_batch)."""
        from elasticdl_tpu.common.sharded_checkpoint import load_sharded

        shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, self._ts
        )
        version, self._ts = load_sharded(directory, shardings)
        return version
