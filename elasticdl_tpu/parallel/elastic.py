"""Multi-process elastic data-parallel training over a global device mesh.

This is the cross-host realization of the ALLREDUCE strategy
(parallel/trainer.py is the single-process form): every worker process
holds a slot in one ``jax.sharding.Mesh`` spanning all hosts, parameters
live replicated in device memory, and the per-step gradient exchange is
the in-step XLA collective. The reference never built this plane (its
allreduce.md is a design survey, SURVEY.md §2.2); the gRPC dense-gradient
round trips it *did* build (GetModel/ReportGradient) are exactly what the
in-mesh collective replaces.

Three problems unique to the elastic multi-process setting, and their
solutions here:

- **Lockstep with independent task queues.** Each process pulls its own
  tasks from the master, so processes run out of data at different
  times — but every process must participate in every collective. The
  step is *weighted*: each device contributes its gradient scaled by a
  0/1 weight, the weighted psum divides by the live count, and the step
  returns that count. A process with no data feeds its previous batch at
  weight 0 and keeps stepping until the global count reaches zero — the
  collective itself is the "anyone still training?" barrier.

- **State continuity across membership epochs.** On a world change the
  worker pulls its addressable replica to host, re-forms the world
  (parallel/distributed.py), and re-places state with
  :func:`broadcast_from_device0`: every process offers its copy, device 0
  (rank 0 = the longest-lived survivor) wins, XLA broadcasts it. A fresh
  joiner offers garbage and receives the survivors' state — replacing the
  reference's workers-re-push-to-PS re-init (ps/servicer.py:70-79).

- **Failure visibility.** A peer death mid-collective surfaces as an
  error from the jitted step on every survivor. Step inputs are not
  donated, so the pre-step state is still addressable afterwards; the
  worker fetches it, waits for the master to bump the epoch, and
  re-forms. (The single-process trainer donates; here the double
  buffering is the price of kill-anywhere recovery.)
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.nn.model_api import apply_model, init_variables, split_variables
from elasticdl_tpu.parallel import compile_plane, distributed, layout_solver
from elasticdl_tpu.parallel.ring_attention import shard_map
from elasticdl_tpu.parallel.sharding import tp_degree_candidates
from elasticdl_tpu.training.step import (
    TrainState,
    accumulate_gradients,
    aux_loss_total,
    block_device_losses,
)
from elasticdl_tpu.utils import profiling


# re-exported: the trainer's historical home for the escapable-call
# machinery; the implementation lives in the leaf module so the
# graft-entry device probe can import it without the training stack
from elasticdl_tpu.common.escapable import (  # noqa: F401
    EscapeTimeout,
    escapable_call,
)


# resize pause distribution, scraped via /metrics: one observation per
# establish(), labeled by whether the step fn came out of the
# executable cache (a PLANNED resize pays state movement only) or had
# to trace/compile
_RESIZE_PAUSE = profiling.metrics.histogram(
    "edl_resize_pause_seconds",
    "establish() wall seconds, world re-form through step-fn acquire",
    labels=("compile_phase",),
)


def build_world_mesh(mesh_axes_fn=None):
    """The elastic world's device mesh.

    Default: every device on one flat ``("data",)`` axis. With a zoo
    ``mesh_axes`` hook, the hook's ``{axis: size}`` layout (insertion
    order = axis order), e.g. ``{"data": n // S, "pipe": S}`` — the
    row-major reshape makes consecutive processes fill the trailing
    axis first, so the first ``S`` processes form one complete pipe
    group (and a world shrink keeps whole groups)."""
    devices = np.asarray(jax.devices())
    axes = mesh_axes_fn(devices.size) if mesh_axes_fn else None
    if not axes:
        return Mesh(devices, ("data",))
    names = tuple(axes)
    sizes = tuple(int(axes[n]) for n in names)
    if int(np.prod(sizes)) != devices.size:
        raise ValueError(
            "mesh_axes %r does not cover the %d-device world"
            % (axes, devices.size)
        )
    return Mesh(devices.reshape(sizes), names)


def row_partition_spec(mesh):
    """Dim-0-over-all-axes PartitionSpec (flattened device order)."""
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def host_copy(tree):
    """Fetch each leaf's process-addressable replica to host numpy."""

    def fetch(x):
        if hasattr(x, "addressable_shards"):
            return np.asarray(x.addressable_shards[0].data)
        return np.asarray(x)

    return jax.tree_util.tree_map(fetch, tree)


def broadcast_from_device0(mesh, host_tree, source_process=0):
    """Place ``host_tree`` replicated on ``mesh``, all processes adopting
    ``source_process``'s copy (default: rank 0).

    Each process tiles its own host copy across its local devices into a
    global (n_devices, ...) array sharded on ``data``; selecting the
    source process's first device row under jit makes XLA broadcast that
    copy to every device. This is both the multi-process placement
    primitive (plain ``device_put`` can't target non-addressable
    shardings) and the survivor-state re-broadcast.
    """
    n_local = jax.local_device_count()
    n_dev = mesh.devices.size
    src_dev = source_process * n_local
    row_axes = row_partition_spec(mesh)[0]

    def place(x):
        x = np.asarray(x)
        tiled = np.broadcast_to(x[None], (n_local,) + x.shape)
        spec = P(*((row_axes,) + (None,) * x.ndim))
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), tiled, (n_dev,) + x.shape
        )

    stacked = jax.tree_util.tree_map(place, host_tree)
    pick = jax.jit(
        lambda t: jax.tree_util.tree_map(lambda a: a[src_dev], t),
        out_shardings=NamedSharding(mesh, P()),
    )
    return pick(stacked)


def _is_sharded_spec(spec):
    return spec is not None and any(a is not None for a in spec)


def _spec_axes(spec):
    """Flat set of mesh axis names a PartitionSpec shards over."""
    used = set()
    for entry in spec or ():
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


class ShardMirror:
    """One rank's in-memory replica of the sharded state plane.

    Captured by :meth:`ElasticDPTrainer.refresh_mirror` (a collective —
    every rank at the same aligned step): this rank's own shards of
    every sharded leaf, a ``ppermute``-received copy of the LEFT
    neighbor process's shards, and a host copy of the replicated leaves
    — all at one consistent ``version``. Any single process death
    leaves every old shard present on some survivor (own everywhere +
    replica on the right neighbor), so a re-form can reassemble the
    full state device-to-device with no disk in the path; the loss
    bound is the refresh cadence. This implements (and betters) the
    replica design the reference specified but never built
    (/root/reference/docs/designs/parameter_server.md:109-131).
    """

    __slots__ = (
        "version",
        "n_old",
        "old_pid",
        "own",
        "replica",
        "replicated",
    )

    def __init__(self, version, n_old, old_pid, own, replica, replicated):
        self.version = version
        self.n_old = n_old  # process count of the world that captured it
        self.old_pid = old_pid  # this rank's process id in that world
        self.own = own  # {path names: np rows of this process's block}
        self.replica = replica  # left neighbor process's block, same keying
        self.replicated = replicated  # host ts; sharded leaves are placeholders


def process_dim0_block(axes, spec, shape0, n_local, pid):
    """(lo, hi) of the contiguous dim-0 rows process ``pid`` holds for a
    leaf whose dim 0 is sharded per ``spec`` on a mesh laid out as
    ``axes`` ({name: size}, insertion order = axis order).

    Derived analytically from the mesh layout — the replica plane needs
    any OLD process's block without that process being alive (its
    mirror holder reconstructs the range from the old world's shape
    alone). Handles any dim-0 sharding: single axis, axis tuples, and
    leaves replicated over part of the mesh (a P("pipe") stage subtree
    on a data x pipe mesh repeats the same range across data groups).
    """
    entry = spec[0] if spec is not None and len(spec) else None
    if entry is None:
        return (0, shape0)
    axs = tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)
    names = tuple(axes)
    sizes = tuple(int(axes[n]) for n in names)
    shard_count = 1
    for a in axs:
        shard_count *= int(axes[a])
    rows = shape0 // shard_count
    starts = set()
    for d in range(pid * n_local, (pid + 1) * n_local):
        coord = dict(zip(names, np.unravel_index(d, sizes)))
        idx = 0
        for a in axs:
            idx = idx * int(axes[a]) + int(coord[a])
        starts.add(idx * rows)
    lo, hi = min(starts), max(starts) + rows
    if hi - lo != len(starts) * rows:
        raise ValueError(
            "process %d holds a non-contiguous dim-0 block for spec %r "
            "on mesh %r" % (pid, spec, axes)
        )
    return (lo, hi)


def _subtract_intervals(lo, hi, covered):
    """Pieces of [lo, hi) not covered by the sorted disjoint list."""
    out = []
    cur = lo
    for s, e in covered:
        if e <= cur:
            continue
        if s >= hi:
            break
        if s > cur:
            out.append((cur, min(s, hi)))
        cur = max(cur, e)
        if cur >= hi:
            break
    if cur < hi:
        out.append((cur, hi))
    return out


def _insert_interval(covered, lo, hi):
    covered.append((lo, hi))
    covered.sort()
    merged = []
    for s, e in covered:
        if merged and s <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((s, e))
    covered[:] = merged


def plan_mirror_ranges(
    info, leaf_blocks, leaf_spans, floor=0, allow_stale=True
):
    """Pure decision core of the replica-plane assembly (range-based).

    ``info``: ``[(has, version, n_old, old_pid)]`` indexed by NEW rank
    (the all-gathered summary — identical on every rank, so this plan
    is too). ``leaf_blocks``: ``{path: fn(old_pid) -> (lo, hi)}`` — the
    dim-0 interval each OLD process owned (its ppermute replica covers
    its LEFT neighbor ``(pid - 1) % n_old``). ``leaf_spans``:
    ``{path: total_rows}``.

    Returns ``(target_v, n_old, {path: [(lo, hi, src_rank, kind)]})``
    with disjoint pieces covering ``[0, total)`` per path (kind 0 =
    the source rank's own block, 1 = its replica), or None:

    - the target version is the newest mirrored version; mirrors from
      an older refresh (a rank that somehow missed one) are excluded,
    - duplicate claims to one old pid keep the lowest new rank,
    - own blocks are preferred over replicas; within a kind the lowest
      rank wins — every rank computes the identical assignment,
    - replication across the old mesh (stage shards repeated over data
      groups) means ANY holder of a row range covers it, which is how
      a pp x dp job survives losing a whole pipe column.
    """
    have = [
        (rank, v, n, pid)
        for rank, (has, v, n, pid) in enumerate(info)
        if has
    ]
    if not have:
        return None
    target_v = max(v for _, v, _, _ in have)
    if not allow_stale and floor > target_v:
        return None
    n_olds = {n for _, v, n, _ in have if v == target_v}
    if len(n_olds) != 1:
        return None
    n_old = n_olds.pop()
    seen_pids = set()
    holders = []  # (new_rank, old_pid), lowest rank keeps a dup pid
    for rank, v, n, pid in sorted(have):
        if v == target_v and n == n_old and pid not in seen_pids:
            seen_pids.add(pid)
            holders.append((rank, pid))
    plan = {}
    for path, block_of in leaf_blocks.items():
        total = leaf_spans[path]
        candidates = [
            (0, rank, block_of(pid)) for rank, pid in holders
        ] + [
            (1, rank, block_of((pid - 1) % n_old))
            for rank, pid in holders
        ]
        covered = []
        pieces = []
        for kind, rank, (lo, hi) in sorted(
            candidates, key=lambda c: (c[0], c[1])
        ):
            for s, e in _subtract_intervals(lo, hi, covered):
                pieces.append((s, e, rank, kind))
                _insert_interval(covered, s, e)
        if covered != [(0, total)]:
            return None
        plan[path] = sorted(pieces)
    return target_v, n_old, plan


def _local_block(arr):
    """(rows ndarray, global row offset) of this process's contiguous
    slice of a row-sharded global array. Deduplicates shards by offset:
    a leaf replicated over part of the mesh (a P("pipe") stage subtree
    on a data x pipe mesh) presents the same rows on several local
    devices, which must not be concatenated twice."""
    by_start = {}
    for s in arr.addressable_shards:
        start = int(s.index[0].start or 0)
        if start not in by_start:
            by_start[start] = np.asarray(s.data)
    starts = sorted(by_start)
    rows = np.concatenate([by_start[s] for s in starts])
    span = sum(by_start[s].shape[0] for s in starts)
    if starts[-1] + by_start[starts[-1]].shape[0] - starts[0] != span:
        raise ValueError("non-contiguous local block")
    return rows, starts[0]


def _max_checkpoint_version(candidate_dirs):
    """Largest ckpt_v{N} among candidate directory paths (0 if none)."""
    import os
    import re

    best = 0
    for d in candidate_dirs or ():
        m = re.match(r"ckpt_v(\d+)$", os.path.basename(str(d)))
        if m:
            best = max(best, int(m.group(1)))
    return best


class PadDim0:
    """Marks a sharded spec whose leaves' dim 0 may be zero-PADDED up
    to the next multiple of the world's shard count, so non-divisor
    world sizes place cleanly (a kill 8 -> 7 keeps training instead of
    erroring). Only sound for leaves whose extra rows are INERT —
    embedding tables, whose rows beyond the declared vocab are never
    addressed (real vocab sizes like GPT-2's 50257 have no divisor
    structure, so Megatron-style padding is the only general answer).
    Leaves with structural dim-0 semantics (stacked pipeline stages)
    must NOT be marked: a zero stage would change the math, and their
    divisibility is kept by the membership layer's world-size rounding
    instead."""

    __slots__ = ("spec",)

    def __init__(self, spec):
        self.spec = spec


def collect_sharded_paths(param_specs):
    """Flatten a nested param_specs dict into {path tuple: PartitionSpec}.

    A ``"**"`` key makes its spec apply to EVERY leaf under the
    enclosing prefix (stored as ``prefix + ("**",)``): the stacked stage
    subtree of a pipeline (parallel/pipeline.py PipelinedStack) has many
    leaves of varying depth that all shard the same way, which per-leaf
    spec paths cannot express. :class:`PadDim0` markers are unwrapped
    (use :func:`collect_paddable_paths` to recover which spec paths
    carried one)."""
    paths = {}
    if not param_specs:
        return paths

    def walk(spec_tree, prefix):
        if hasattr(spec_tree, "items"):
            for k, sub in spec_tree.items():
                walk(sub, prefix + (k,))
        else:
            if isinstance(spec_tree, PadDim0):
                spec_tree = spec_tree.spec
            paths[prefix] = spec_tree

    walk(param_specs, ())
    return paths


def collect_paddable_paths(param_specs):
    """Spec paths whose leaves were marked :class:`PadDim0`."""
    paddable = set()
    if not param_specs:
        return paddable

    def walk(spec_tree, prefix):
        if hasattr(spec_tree, "items"):
            for k, sub in spec_tree.items():
                walk(sub, prefix + (k,))
        elif isinstance(spec_tree, PadDim0):
            paddable.add(prefix)

    walk(param_specs, ())
    return paddable


def dim0_shard_count(spec, axes):
    """How many ways a leaf's dim 0 splits on a mesh laid out ``axes``."""
    entry = spec[0] if spec is not None and len(spec) else None
    if entry is None:
        return 1
    axs = tuple(entry) if isinstance(entry, (tuple, list)) else (entry,)
    count = 1
    for a in axs:
        count *= int(axes[a])
    return count


def padded_dim0(shape0, spec, axes):
    """dim 0 rounded UP to the next multiple of its shard count."""
    count = dim0_shard_count(spec, axes)
    return -(-int(shape0) // count) * count


def spec_path_matches(spec_path, leaf_names):
    """True when a collected spec path claims a leaf's tree path.

    Exact paths match by suffix (so optimizer slot trees, which nest the
    params structure under mu/nu/..., co-shard automatically). Subtree
    paths (ending in ``"**"``) match when their prefix appears as a
    contiguous run anywhere in the leaf path."""
    names = tuple(leaf_names)
    if spec_path and spec_path[-1] == "**":
        prefix = tuple(spec_path[:-1])
        if not prefix:
            return True
        span = len(prefix)
        return any(
            names[i : i + span] == prefix
            for i in range(len(names) - span + 1)
        )
    return names[-len(spec_path):] == tuple(spec_path)


def build_state_specs(ts, sharded_paths):
    """TrainState-shaped PartitionSpec pytree for the elastic step.

    Leaves whose tree path *ends with* a sharded path get that path's
    spec — matching both the parameters and their optimizer slots (optax
    moment trees nest the same sub-structure) — everything else ``P()``.
    """
    from elasticdl_tpu.common.pytree import key_path_names

    def spec_for(key_path, _leaf):
        names = key_path_names(key_path)
        for spec_path, spec in sharded_paths.items():
            if spec_path_matches(spec_path, names):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, ts)


def place_from_host_specs(mesh, tree, spec_tree):
    """Place a full host pytree on a (possibly multi-process) mesh per a
    matching spec pytree; each process materializes only its own
    devices' slices (``make_array_from_callback``)."""

    def put(x, spec):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape,
            NamedSharding(mesh, spec),
            lambda idx, x=x: x[idx],
        )

    return jax.tree_util.tree_map(put, tree, spec_tree)


def optimizer_couples_leaves(optimizer):
    """Behavioral probe: does one leaf's update depend on ANOTHER leaf's
    gradient?

    On the sharded-state plane each rank holds different local table
    shards, so a cross-leaf transform (``optax.clip_by_global_norm`` is
    the common one) folds each rank's different shard gradients into a
    per-rank scale and silently desynchronizes the replicated
    parameters. Probing behavior instead of matching transform names
    catches every such transform, including ones inside ``optax.chain``
    or custom ``GradientTransformation``s. Probes a tiny 2-leaf tree:
    changing only leaf b's gradient must not change leaf a's update.
    """
    import jax.numpy as jnp

    probe = {
        "a": jnp.ones((4,), jnp.float32),
        "b": jnp.ones((4,), jnp.float32),
    }
    try:
        state = optimizer.init(probe)
        g_small = {
            "a": jnp.full((4,), 0.5, jnp.float32),
            "b": jnp.full((4,), 0.5, jnp.float32),
        }
        g_large = {
            "a": jnp.full((4,), 0.5, jnp.float32),
            "b": jnp.full((4,), 64.0, jnp.float32),
        }
        u1, _ = optimizer.update(g_small, state, probe)
        u2, _ = optimizer.update(g_large, state, probe)
    except Exception:
        # exotic optimizer the probe can't drive: let training proceed —
        # this check exists to catch the common silent footgun, not to
        # gate every optimizer shape
        logger.warning(
            "optimizer cross-leaf probe failed; skipping the sharded-"
            "plane coupling check",
            exc_info=True,
        )
        return False
    return not np.allclose(
        np.asarray(u1["a"]), np.asarray(u2["a"]), rtol=1e-6, atol=1e-8
    )


def make_elastic_train_step(
    module,
    loss_fn,
    optimizer,
    mesh,
    axis=None,
    precision=None,
    accum_steps=1,
    state_specs=None,
    remat=False,
):
    """Weighted lockstep step: ``(ts, features, labels, weights, epochs,
    rng) -> (ts', loss, n_active, epoch_consensus)``.

    Works over ANY mesh axis layout: ``axis`` defaults to the mesh's
    full axis-name tuple, the batch/weights/epochs shard over the
    flattened device order, and reductions run over exactly the axes a
    leaf is NOT sharded over — so a ``("data", "pipe")`` mesh reduces a
    replicated leaf over both axes, a stage-sharded ``P("pipe")`` leaf
    over ``data`` only, and a vocab-sharded ``P("data", None)`` leaf
    over ``pipe`` only (its data-axis row gradients were already routed
    by the collective lookup's a2a backward).

    ``epochs`` is a global (n_devices,) int32 of each process's
    last-polled membership epoch; ``epoch_consensus`` is its in-step
    pmax — the skew-proof pause signal (see the per_device comment).

    ``weights`` is a global (n_devices,) 0/1 array — per-device
    participation. The local loss is scaled by ``w / psum(w)`` INSIDE the
    differentiated function, so every gradient contribution — including
    row gradients an ``all_to_all`` transpose routes to other devices'
    table shards — carries its device's weight at the source; replicated
    leaves then just psum. With zero live devices the state passes
    through unchanged and ``version`` does not advance, so drain-mode
    dummy steps are exact no-ops.

    ``state_specs``: optional pytree with the SAME treedef as the
    TrainState, each leaf a PartitionSpec — ``P()`` for replicated
    leaves, e.g. ``P("data", None)`` for HBM-sharded embedding tables
    (and their co-sharded optimizer slots), ``P("pipe")`` for stacked
    pipeline-stage subtrees. Sharded leaves enter the step as
    their local shard, and the module must use raw in-step collectives
    (nn/hbm_embedding.py ``collective=True``, pipeline.PipelinedStack
    ``collective=True``) since a nested shard_map is impossible here.
    Constraint: the optimizer must
    be per-leaf elementwise (sgd/momentum/adam/adagrad/... all are) —
    a transform that couples across leaves, e.g.
    ``optax.clip_by_global_norm``, would fold each device's DIFFERENT
    local table-shard gradient into a per-device scale and silently
    desynchronize the replicated parameters.

    ``precision``: a training.precision.Policy (or preset name); master
    weights, gradients, and the weighted psum math stay in
    ``param_dtype`` — only the forward/backward compute casts down.

    ``accum_steps > 1``: each device scans its local batch in
    microbatches before the weighted reduction (semantics of
    training/step.py:make_train_step accumulation; the participation
    weight applies to the accumulated mean, so elasticity/tail-batch
    weighting is unchanged). The trainer pads local rows to a multiple
    of ``accum_steps * local_devices``.
    """
    from elasticdl_tpu.training.precision import get_policy
    from elasticdl_tpu.training.step import make_remat_forward

    pol = get_policy(precision)
    forward = make_remat_forward(module, remat)
    if axis is None:
        axis = tuple(mesh.axis_names)
    axes = axis if isinstance(axis, tuple) else (axis,)

    def _is_sharded(spec):
        return spec is not None and any(a is not None for a in spec)

    def _unsharded_axes(spec):
        """Mesh axes a leaf is replicated over (its reduction axes)."""
        used = _spec_axes(spec)
        return tuple(a for a in axes if a not in used)

    def per_device(ts, features, labels, weights, epochs, rng):
        w = weights[0].astype(jnp.float32)
        # membership-epoch consensus rides the step: each process feeds
        # the epoch it last polled, the pmax tells EVERY member (at the
        # same step index — it is the same collective) the newest epoch
        # any member has seen. Pausing on this consensus at aligned sync
        # indices is skew-proof: polled-epoch observation happens at
        # different host iterations once deferred sync lets hosts run
        # ahead, and a member pausing early strands peers' in-flight
        # dispatched steps on a vanished rank.
        epoch_seen = jax.lax.pmax(epochs[0], axes)
        # decorrelate stochastic layers (dropout) across the batch shards
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axes))
        # liveness (how many devices carried data) is separate from the
        # weighted denominator: tail batches contribute fractional weight
        n = jax.lax.psum((w > 0).astype(jnp.float32), axes)
        denom = jnp.maximum(jax.lax.psum(w, axes), 1e-6)
        scale = w / denom

        def grads_of(state, features_mb, labels_mb, rng_mb):
            def loss_of(p):
                if pol is not None:
                    p = pol.cast_to_compute(p)
                    features_c = pol.cast_to_compute(features_mb)
                else:
                    features_c = features_mb
                output, new_state = forward(
                    p, state, features_c, rng_mb
                )
                if pol is not None:
                    output = pol.cast_output(output)
                raw = loss_fn(output, labels_mb) + aux_loss_total(
                    new_state
                )
                # the weight rides the loss so AD distributes it to
                # every gradient contribution, local or routed
                return raw * scale, (raw, new_state)

            (_, (raw, new_state)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(ts.params)
            return raw, grads, new_state

        if accum_steps == 1:
            loss, grads, new_state = grads_of(
                ts.state, features, labels, rng
            )
        else:
            loss, grads, new_state = accumulate_gradients(
                grads_of,
                ts.state,
                features,
                labels,
                rng,
                accum_steps,
                ts.params,
            )

        if state_specs is None:
            grad_specs = jax.tree_util.tree_map(lambda _: None, grads)
            state_spec_tree = jax.tree_util.tree_map(
                lambda _: None, new_state
            )
        else:
            grad_specs = state_specs.params
            state_spec_tree = state_specs.state

        def reduce_grad(g, spec):
            # reduce over exactly the axes the leaf replicates over:
            # all of them for dense leaves, none for a fully-sharded
            # table on a 1-axis mesh (weighting rode the loss, the a2a
            # backward already routed row gradients), the data axes for
            # a P("pipe") stage subtree (stage replicas across data
            # groups must agree)
            red = _unsharded_axes(spec)
            return jax.lax.psum(g, red) if red else g

        grads = jax.tree_util.tree_map(reduce_grad, grads, grad_specs)
        loss = jax.lax.psum(loss * scale, axes)

        def wavg(x, spec):
            if _is_sharded(spec):
                return x  # per-shard state stays local
            if jnp.issubdtype(x.dtype, jnp.floating):
                return jax.lax.psum(x * w, axes) / denom
            return x  # int leaves (counters) advance identically everywhere

        new_state = jax.tree_util.tree_map(
            wavg, new_state, state_spec_tree
        )

        updates, opt_state = optimizer.update(grads, ts.opt_state, ts.params)
        params = optax.apply_updates(ts.params, updates)
        live = n > 0

        def select(new, old):
            return jnp.where(live, new, old)

        new_ts = TrainState(
            params=jax.tree_util.tree_map(select, params, ts.params),
            state=jax.tree_util.tree_map(select, new_state, ts.state),
            opt_state=jax.tree_util.tree_map(select, opt_state, ts.opt_state),
            version=ts.version + live.astype(jnp.int32),
        )
        return new_ts, loss, n, epoch_seen

    if state_specs is None:
        ts_spec = P()
    else:
        ts_spec = state_specs
    # batch/weights/epochs shard dim 0 over the FLATTENED device order,
    # so each process's rows land on its own devices whatever the mesh
    # shape (same layout the trainer places them with)
    row_spec = row_partition_spec(mesh)
    sharded = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(ts_spec, row_spec, row_spec, row_spec, row_spec, P()),
        out_specs=(ts_spec, P(), P(), P()),
        check_rep=False,
    )
    # no donation: the pre-step state must survive a failed collective so
    # survivors can re-form from it (see module docstring)
    return jax.jit(sharded)


def specs_use_axis(sharded_paths, axis):
    """True when any collected spec shards over ``axis`` — the pjit
    dense-path trigger is ``specs_use_axis(paths, "model")``."""
    return any(
        axis in _spec_axes(spec) for spec in (sharded_paths or {}).values()
    )


def derive_model_profile(abstract_ts, state_specs):
    """:class:`layout_solver.ModelProfile` from the abstract TrainState
    and its spec tree — the layout solver's deterministic model input.

    Everything here is a function of the model/optimizer structure
    alone (shapes, dtypes, which leaves shard over ``model``), so every
    process derives the identical profile and the solver's establish
    picks agree without any exchange. The flop/activation terms are
    RELATIVE proxies (6*N flops per example row, activation volume
    proportional to the total model-sharded width); telemetry
    calibration supplies real constants when ordering alone isn't
    enough (layout_solver module docstring)."""
    replicated_bytes = 0.0
    tp_bytes = 0.0
    model_dims = []

    def visit(leaf, spec):
        nonlocal replicated_bytes, tp_bytes
        shape = tuple(leaf.shape)
        nbytes = float(np.prod(shape)) * np.dtype(leaf.dtype).itemsize
        axes = tuple(spec) if spec is not None else ()
        if "model" in axes:
            tp_bytes += nbytes
            model_dims.append(int(shape[axes.index("model")]))
        else:
            replicated_bytes += nbytes

    jax.tree_util.tree_map(visit, abstract_ts, state_specs)
    param_count = sum(
        float(np.prod(tuple(leaf.shape)))
        for leaf in jax.tree_util.tree_leaves(abstract_ts.params)
    )
    return layout_solver.ModelProfile(
        replicated_bytes=replicated_bytes,
        tp_bytes=tp_bytes,
        activation_bytes_per_row=4.0 * float(sum(model_dims)),
        flops_per_row=6.0 * param_count,
        tp_degrees=tp_degree_candidates(model_dims),
    )


def make_pjit_train_step(
    module,
    loss_fn,
    optimizer,
    mesh,
    state_specs,
    precision=None,
    remat=False,
):
    """GSPMD weighted lockstep step — the pjit dense plane.

    Same call signature and external semantics as
    :func:`make_elastic_train_step` (``(ts, features, labels, weights,
    epochs, rng) -> (ts', loss, n_active, epoch_consensus)``), but the
    body is GLOBAL-semantics math under ``jax.jit`` with
    ``NamedSharding`` out-shardings: XLA partitions the dense model per
    the spec tree and inserts the tensor-parallel collectives itself —
    the "Scalable Training of Language Models using JAX pjit and
    TPUv4" blueprint (PAPERS.md 2204.06514) inside the elastic world.
    The module is the PLAIN flax model (no raw in-step collectives, no
    collective zoo form): correctness is placement-independent, so the
    same module trains replicated or 2D ``data x model`` sharded and
    the specs only decide layout.

    Elasticity semantics carried over from the shard_map step:

    - per-device participation ``weights`` scale each device block's
      loss contribution INSIDE the differentiated function
      (:func:`training.step.block_device_losses` recovers the
      per-device granularity from the global batch), so tail batches
      and drain-mode zero-weight devices weight gradients identically
      to the replicated arm;
    - ``epochs``' max is the membership-epoch consensus (the global
      ``jnp.max`` IS the pmax — same collective, spelled globally);
    - with zero live devices the state passes through unchanged and
      ``version`` does not advance.

    Differences, by design: dropout draws ONE global rng (no
    per-device fold-in — parity for stochastic layers is per-batch,
    not per-device), mutable model state (batch stats) updates from
    the full global batch including weight-0 devices' stale rows (use
    the replicated plane for batch-stat models), and the MoE aux loss
    adds once globally rather than per device. No donation, same as
    the elastic step: the pre-step state must survive a failed
    collective for re-forms.
    """
    from elasticdl_tpu.training.precision import get_policy
    from elasticdl_tpu.training.step import make_remat_forward

    pol = get_policy(precision)
    forward = make_remat_forward(module, remat)
    n_dev = mesh.devices.size
    rep = NamedSharding(mesh, P())
    ts_shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), state_specs
    )

    def step(ts, features, labels, weights, epochs, rng):
        w = weights.astype(jnp.float32)  # (n_dev,)
        n = jnp.sum((w > 0).astype(jnp.float32))
        denom = jnp.maximum(jnp.sum(w), 1e-6)
        epoch_seen = jnp.max(epochs)

        def loss_of(p):
            if pol is not None:
                p = pol.cast_to_compute(p)
                features_c = pol.cast_to_compute(features)
            else:
                features_c = features
            output, new_state = forward(p, ts.state, features_c, rng)
            if pol is not None:
                output = pol.cast_output(output)
            dev_raw = block_device_losses(loss_fn, output, labels, n_dev)
            # the weight rides the loss so AD distributes it to every
            # gradient contribution (the same trick as the shard_map
            # step — there via scale, here via the weighted block sum)
            raw = jnp.sum(dev_raw * w) / denom + aux_loss_total(new_state)
            return raw, new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(ts.params)
        updates, opt_state = optimizer.update(grads, ts.opt_state, ts.params)
        params = optax.apply_updates(ts.params, updates)
        live = n > 0

        def select(new, old):
            return jnp.where(live, new, old)

        new_ts = TrainState(
            params=jax.tree_util.tree_map(select, params, ts.params),
            state=jax.tree_util.tree_map(select, new_state, ts.state),
            opt_state=jax.tree_util.tree_map(
                select, opt_state, ts.opt_state
            ),
            version=ts.version + live.astype(jnp.int32),
        )
        return new_ts, loss, n, epoch_seen

    # out-shardings PIN the layout: without them XLA could silently
    # re-replicate a sharded parameter on the way out and the "bigger
    # than one device" property would evaporate after the first step
    return jax.jit(
        step, out_shardings=(ts_shardings, rep, rep, rep)
    )


class _BatchFeeder:
    """One-slot async H2D stager (the compile plane's step-overlap leg).

    The worker hands the NEXT batch over right before a blocking sync
    step, and this daemon thread pads + places it onto the mesh while
    the training thread sits in the device->host fetch — so the hot
    loop never serializes H2D behind D2H. Single producer, single
    consumer (both the training thread); the worker thread only runs
    the placement callable. A placement that errors or outlives
    ``take``'s wait degrades to inline placement in the caller — the
    feeder is an overlap optimization, never a correctness dependency.
    """

    def __init__(self, place_fn, name="edl-h2d-feeder"):
        self._place_fn = place_fn
        self._lock = threading.Lock()
        self._work = None  # (token, payload) awaiting placement
        self._token = None  # token of the staged (completed) result
        self._result = None
        self._staged_token = None  # token most recently handed to stage()
        self._ready = threading.Event()
        self._wake = threading.Event()
        self._cancel = threading.Event()
        self._broken = False
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def stage(self, token, payload):
        """Queue one placement; a newer stage replaces an unstarted one."""
        if self._broken or self._cancel.is_set():
            return
        with self._lock:
            self._work = (token, payload)
            self._staged_token = token
            self._ready.clear()
        self._wake.set()

    def _run(self):
        while not self._cancel.is_set():
            if not self._wake.wait(timeout=0.2):
                continue
            with self._lock:
                work, self._work = self._work, None
                self._wake.clear()
            if work is None:
                continue
            token, payload = work
            try:
                result = self._place_fn(*payload)
            except Exception:
                # surfaced as a take() miss; the caller re-places inline
                # and gets the real error there if it reproduces
                logger.warning(
                    "async batch placement failed; falling back to "
                    "inline placement",
                    exc_info=True,
                )
                result = None
            with self._lock:
                self._token, self._result = token, result
                self._ready.set()

    def take(self, token, timeout=30.0, should_abort=None):
        """The staged placement for ``token``, or None (not staged /
        superseded / failed / timed out / aborted). The wait polls
        ``should_abort`` (the trainer's wedge-escape probe) in short
        slices: a placement wedged on a dead transport must not hold
        the training thread past the world moving on. A timeout or an
        abort marks the feeder broken — a wedged device transport must
        not be probed twice."""
        with self._lock:
            if self._broken or self._staged_token != token:
                return None
        deadline = time.monotonic() + timeout
        while not self._ready.wait(0.5):
            aborted = False
            if should_abort is not None:
                try:
                    aborted = should_abort()
                except Exception:
                    logger.debug(
                        "feeder abort probe failed", exc_info=True
                    )
            if aborted or time.monotonic() >= deadline:
                self._broken = True
                logger.warning(
                    "async batch placement still running (%s); feeder "
                    "disabled for this world",
                    "world moved on" if aborted else "timeout",
                )
                return None
        with self._lock:
            if self._token != token:
                return None
            result, self._result = self._result, None
            self._token = None
            self._staged_token = None
            return result

    def shutdown(self, timeout=5.0):
        self._cancel.set()
        self._wake.set()
        t = self._thread
        if t.is_alive():
            t.join(timeout=timeout)


class ElasticDPTrainer:
    """Per-process handle on the global elastic DP training plane."""

    def __init__(
        self,
        module,
        loss_fn,
        optimizer,
        seed=0,
        precision=None,
        accum_steps=1,
        distributed_builder=None,
        restore_provider=None,
        remat=False,
        mesh_axes_fn=None,
        layout_planner=None,
    ):
        """``distributed_builder``: optional ``mesh -> (module,
        param_specs)`` hook for HBM-sharded parameters (the zoo's
        ``build_collective_model`` + ``param_shardings``). Sharded
        leaves cannot ride the survivor re-broadcast (a dead process's
        shards are gone), so re-forms restore the WHOLE state from
        ``restore_provider()`` (the latest sharded checkpoint directory,
        or None) — recovery granularity is the checkpoint cadence; with
        no checkpoint the state re-initializes (the reference lost its
        Redis-resident tables entirely on the same failure,
        reference master/embedding_service.py).

        ``mesh_axes_fn``: optional ``n_devices -> {axis: size} | None``
        (the zoo's ``mesh_axes`` hook) — the elastic world's mesh
        layout, e.g. ``{"data": n // S, "pipe": S}`` for a pipelined
        model. None/absent means the flat 1-axis ``("data",)`` mesh.
        Raises at establish if the world size doesn't fit (the
        membership layer's world_size_multiple exists to prevent such
        worlds from forming).

        ``layout_planner``: optional
        :class:`layout_solver.LayoutPlanner` — resizes then RE-SOLVE
        the dp x tp layout instead of replaying the static
        ``mesh_axes_fn`` (which becomes the planner's fallback until
        the first establish derives the model profile). pjit-dense jobs
        only; see docs/distributed.md "Layout re-solve"."""
        self._module = module
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._coupling_checked = False
        self._seed = seed
        self._precision = precision
        self._remat = remat
        self._accum_steps = max(1, accum_steps)
        self._builder = distributed_builder
        self._planner = layout_planner
        if layout_planner is not None:
            if layout_planner.fallback_axes_fn is None:
                layout_planner.fallback_axes_fn = mesh_axes_fn
            mesh_axes_fn = layout_planner.axes_for
        self._mesh_axes_fn = mesh_axes_fn
        self.restore_provider = restore_provider
        self._sharded_paths = {}
        self._paddable_spec_paths = set()
        self._logical_dim0 = {}  # padded leaves: path names -> true dim0
        self._state_specs = None
        # pjit dense plane: specs shard over the "model" axis, the
        # PLAIN module trains under make_pjit_train_step, and resizes
        # re-solve the layout by moving state directly between old and
        # new NamedShardings (docs/distributed.md)
        self._pjit_dense = False
        self._placed_epoch = None  # backend epoch the state was placed in
        self._mesh = None
        self._spec = None
        self._ts = None
        self._checked_ts = None  # last fetch-validated device state
        self._host_ts = None  # latest host snapshot (re-form source)
        self._step_fn = None
        self._eval_fn = None  # in-plane eval forward (built on demand)
        self._gather_fns = {}  # cached per-width info gathers
        self._host_step = 0
        self._last_local = None  # (features, labels) for weight-0 steps
        self.epoch_consensus = None  # newest epoch any member has seen
        # in-memory replica plane (sharded jobs): see ShardMirror
        self.mirror_steps = 0  # 0 disables; worker sets from its flag
        self._mirror = None
        self._mirror_perm_fn = None
        self._last_mirror_version = -1
        # escapable-wait hook (see _escapable): worker sets it to a
        # "has the master already bumped past my epoch?" probe
        self.abort_check = None
        self._wedged = False
        # -- compile-plane fast path (parallel/compile_plane.py) --------
        # executable reuse across establishes: re-forming at a
        # previously-seen (mesh, step-config) hands back the same jitted
        # callable, so jax's aval cache dispatches without retracing
        self.compile_cache_enabled = True
        self._exec_cache = compile_plane.ExecutableCache()
        self.compile_stats = self._exec_cache.stats
        self._step_entry = None  # cache entry backing _step_fn (or None)
        # speculative AOT compiles for likely next world sizes; the
        # worker (or bench) opts in and feeds membership hints
        self.speculative_compile = False
        self._spec_compiler = None
        self._spec_example = None  # host example batch (abstract args)
        # worker's fixed minibatch: lets speculation derive batch shapes
        self.default_minibatch_size = None
        # step overlap: async H2D stager + deferred (collect-later)
        # loss fetches drained at sync/log boundaries
        self._feeder = None
        self._pending_metrics = []  # device loss scalars of unsynced steps
        self._pending_metrics_overflowed = False  # warn once per overflow

    @property
    def mesh(self):
        return self._mesh

    @property
    def version(self):
        if self._ts is None:
            return -1
        # escapable: a peer loss can wedge any device interaction
        return int(
            self._escapable(lambda: host_copy(self._ts.version))
        )

    @property
    def has_state(self):
        """Cheap liveness check (no device->host transfer)."""
        return self._ts is not None or self._host_ts is not None

    @property
    def is_sharded(self):
        """True when parameters shard over the mesh (HBM tables)."""
        return bool(self._sharded_paths) or self._builder is not None

    def _build_init_ts(self, example_batch):
        features = example_batch[0]
        # slice before transfer: a device leaf would otherwise D2H the
        # full batch just to keep one example (same fix as
        # AllReduceTrainer.init_from_batch)
        host_one = jax.tree_util.tree_map(
            lambda x: np.asarray(x[:1]), features
        )

        def build():
            variables = init_variables(
                self._module, jax.random.PRNGKey(self._seed), host_one
            )
            params, state = split_variables(variables)
            return TrainState.create(params, state, self._optimizer)

        return build

    def _host_init_ts(self, example_batch):
        """Deterministic full host init (identical on every process)."""
        return host_copy(self._build_init_ts(example_batch)())

    def _abstract_ts(self, example_batch):
        """ShapeDtypeStruct TrainState — treedef/shapes without
        materializing any parameter values."""
        return jax.eval_shape(self._build_init_ts(example_batch))

    def establish(self, spec, example_batch=None):
        """Join ``spec``'s world and (re)place train state on its mesh.

        ``example_batch`` is required the first time (state init); on
        re-forms the previous host snapshot is re-broadcast, with rank 0
        as the source of truth. Sharded-parameter jobs instead restore
        from the latest checkpoint on EVERY establish (see __init__).
        """
        import time as _time

        # compile-plane helpers target the OLD backend: a speculative
        # compile or an async placement racing the teardown below would
        # wedge against dying devices — stop them first (edlint R4
        # ownership; threads are daemons, a stuck C++ compile is
        # abandoned safely)
        self._shutdown_compile_helpers()
        t0 = _time.time()
        old_layout = self._layout_fields()
        # the planner must answer from the profile on EVERY process
        # from its very first establish (see _maybe_derive_profile),
        # so derive it before the mesh below is laid out
        self._maybe_derive_profile(example_batch)
        profiling.events.emit(
            "resize_begin",
            epoch=spec.epoch,
            rank=spec.process_id,
            world_size=spec.num_processes,
            layout=old_layout,
        )
        distributed.ensure_world(spec)
        t_world = _time.time()
        self._spec = spec
        self._mesh = build_world_mesh(self._mesh_axes_fn)
        # mesh changed: drop EVERY cached jitted callable bound to the
        # old mesh before anything below (the establish-time
        # _replicated_source_rank/_gather_mirror_info all-gathers) can
        # run — a cached fn executed against the dead world's mesh
        # would wedge or corrupt the re-form
        self._mirror_perm_fn = None
        self._eval_fn = None
        self._gather_fns = {}
        self._wedged = False  # fresh backend: device fetches are safe again
        if self._builder is not None:
            self._module, param_specs = self._builder(self._mesh)
            self._sharded_paths = collect_sharded_paths(param_specs)
            self._paddable_spec_paths = collect_paddable_paths(
                param_specs
            )
        self._pjit_dense = specs_use_axis(self._sharded_paths, "model")
        if self._pjit_dense and self._accum_steps > 1:
            raise ValueError(
                "accum_steps > 1 is not supported on the pjit dense "
                "plane yet: global-batch microbatching would regroup "
                "rows across devices and change the weighted-step "
                "semantics — use the replicated plane, or accum_steps=1"
            )
        self._check_optimizer_coupling()
        t_init = t_world
        if self._sharded_paths:
            self._establish_sharded(example_batch)
            t_init = _time.time()  # restore/assembly/init, all of it
        else:
            if example_batch is None and self._host_ts is None:
                raise ValueError(
                    "first establish() needs an example batch"
                )
            # who actually holds replicated state? The broadcast adopts
            # the LOWEST such rank's copy; a fresh joiner then offers a
            # zeros stand-in built from eval_shape (milliseconds)
            # instead of paying a full real host init (~11 s measured
            # for the promoted-standby establish, BASELINE.md r5) that
            # the broadcast would overwrite anyway. Only when NOBODY
            # has state (first formation, or every process died) does
            # each member real-init — deterministically identical, so
            # rank 0's copy is the same init everywhere.
            source = self._replicated_source_rank()
            if source < 0:
                if self._host_ts is None:
                    self._host_ts = self._host_init_ts(example_batch)
                offer, source = self._host_ts, 0
            elif self._host_ts is not None:
                offer = self._host_ts
            else:
                abstract = self._abstract_ts(example_batch)
                offer = jax.tree_util.tree_map(
                    lambda leaf: np.zeros(leaf.shape, leaf.dtype),
                    abstract,
                )
            t_init = _time.time()
            self._ts = broadcast_from_device0(
                self._mesh, offer, source_process=source
            )
        t_place = _time.time()
        self._checked_ts = self._ts
        self._placed_epoch = distributed.backend_epoch()
        self._spec_example = example_batch or self._last_local
        with profiling.annotate("elastic/establish/compile"):
            cache_hit = self._acquire_step_fn()
        t_compile = _time.time()
        logger.info(
            "establish timing: world %.1fs, init %.1fs, place %.1fs, "
            "compile %.1fs (%s)",
            t_world - t0,
            t_init - t_world,
            t_place - t_init,
            t_compile - t_place,
            "cache hit" if cache_hit else "cache miss",
        )
        compile_phase = "cache_hit" if cache_hit else "cache_miss"
        profiling.events.emit(
            "resize_end",
            epoch=spec.epoch,
            rank=spec.process_id,
            world_size=spec.num_processes,
            world_s=round(t_world - t0, 3),
            init_s=round(t_init - t_world, 3),
            place_s=round(t_place - t_init, 3),
            compile_s=round(t_compile - t_place, 3),
            compile_phase=compile_phase,
            cache_hit=bool(cache_hit),
            resize_layout={
                "old": old_layout,
                "new": self._layout_fields(),
            },
        )
        _RESIZE_PAUSE.observe(
            t_compile - t0, compile_phase=compile_phase
        )
        self._start_speculative_compiler()
        if self.mirror_enabled():
            # every rank reaches this point during formation, so the
            # refresh collective is aligned; it also resets
            # _last_mirror_version identically on every rank (joiners
            # included), keeping the cadence predicate global. A
            # FAILED refresh (a peer death racing this formation — the
            # collective fails on every rank together) must not crash
            # the worker out of an otherwise-recoverable establish:
            # swallow it, and advance the cadence marker so the ranks'
            # next-refresh predicate stays aligned whatever mix of
            # old mirrors they keep (the planner version-filters stale
            # ones); the broken world surfaces at the first step and
            # takes the ordinary recovery path
            try:
                self.refresh_mirror()
            except Exception:
                logger.warning(
                    "establish-tail replica refresh failed; the next "
                    "cadence point (or re-form) retries",
                    exc_info=True,
                )
                try:
                    self._last_mirror_version = self.version
                except Exception:
                    # device also wedged: the step failure owns it
                    logger.debug(
                        "cadence marker refresh failed too",
                        exc_info=True,
                    )
        logger.info(
            "elastic plane established: epoch=%d rank=%d/%d devices=%d%s",
            spec.epoch,
            spec.process_id,
            spec.num_processes,
            self._mesh.devices.size,
            " (sharded params)" if self._sharded_paths else "",
        )

    def _layout_fields(self):
        """``{"dp", "tp", "microbatch"}`` of the CURRENT mesh — the
        ``resize_layout`` event payload (None before any establish).
        dp/tp come from the live mesh shape, so the fields are truthful
        whether a planner, a static hook, or the flat default laid the
        world out."""
        if self._mesh is None:
            return None
        shape = dict(self._mesh.shape)
        mb = None
        if (
            self._planner is not None
            and self._planner.last_plan is not None
        ):
            mb = int(self._planner.last_plan.layout.microbatch)
        elif self.default_minibatch_size:
            mb = int(self.default_minibatch_size)
        return {
            "dp": int(shape.get("data", self._mesh.devices.size)),
            "tp": int(shape.get("model", 1)),
            "microbatch": mb,
        }

    def _maybe_derive_profile(self, example_batch):
        """Feed the layout planner its model profile BEFORE the first
        mesh is laid out. Determinism is the point: ``axes_for`` must
        answer from the profile on EVERY process from its very first
        establish — if a fresh joiner solved from the static fallback
        while survivors solved from a profile, the consensus world
        would form over diverging meshes. The probe is mesh-free
        (builder(None) — the same convention the worker's pjit-dense
        probe uses) and abstract (eval_shape): no device work, and the
        numbers are a pure function of the model structure."""
        planner = self._planner
        if planner is None or planner.profile is not None:
            return
        if self._builder is None:
            return
        example = (
            example_batch
            if example_batch is not None
            else self._last_local
        )
        if example is None:
            return
        try:
            _, param_specs = self._builder(None)
            sharded = collect_sharded_paths(param_specs)
            if not specs_use_axis(sharded, "model"):
                return
            abstract = self._abstract_ts(example)
            specs = build_state_specs(abstract, sharded)
            planner.set_profile(derive_model_profile(abstract, specs))
        except Exception:
            logger.warning(
                "layout-planner profile derivation failed; the static "
                "mesh_axes fallback stays in effect",
                exc_info=True,
            )

    def _check_optimizer_coupling(self):
        """Refuse cross-leaf optimizers for sharded-parameter jobs.

        Runs at the FIRST establish, after ``ensure_world`` — the probe
        executes real (tiny) JAX computation, and any JAX computation
        before ``jax.distributed.initialize`` would pin the backend and
        make the world formation itself fail. Once per trainer: the
        optimizer doesn't change across re-forms."""
        if self._coupling_checked or not self._sharded_paths:
            return
        self._coupling_checked = True
        if not optimizer_couples_leaves(self._optimizer):
            return
        import os

        if os.environ.get("EDL_ALLOW_CROSS_LEAF_OPT"):
            logger.warning(
                "cross-leaf optimizer on the sharded plane allowed by "
                "EDL_ALLOW_CROSS_LEAF_OPT=1; replicated parameters may "
                "silently desynchronize"
            )
            return
        # fail before the first step, not N steps into silent divergence
        raise ValueError(
            "the optimizer couples gradients across leaves (e.g. "
            "optax.clip_by_global_norm) but this job shards parameters "
            "across ranks: each rank would fold its own DIFFERENT local "
            "table-shard gradients into the coupled quantity and the "
            "replicated parameters would silently desynchronize. Use "
            "per-leaf transforms instead (e.g. optax.clip / "
            "optax.adaptive_grad_clip), or set "
            "EDL_ALLOW_CROSS_LEAF_OPT=1 if the coupling is known to "
            "exclude the sharded leaves."
        )

    # -- compile-plane fast path (parallel/compile_plane.py) ---------------

    def _step_config_signature(self, state_specs):
        """Everything the step builder closes over besides the mesh:
        two cache entries may share an executable only when ALL of it
        matches (specs included — a stale spec tree would shard-map the
        state wrong, not just run slow)."""
        return (
            id(self._module),
            id(self._optimizer),
            id(self._loss_fn),
            id(self._precision),
            int(self._accum_steps),
            str(self._remat),
            # the pjit dense plane builds a DIFFERENT step callable for
            # the same (module, specs): the flag must key the cache
            bool(self._pjit_dense),
            compile_plane.spec_signature(state_specs),
        )

    def _build_step_fn(self, mesh, state_specs):
        if self._pjit_dense:
            return make_pjit_train_step(
                self._module,
                self._loss_fn,
                self._optimizer,
                mesh,
                state_specs,
                precision=self._precision,
                remat=self._remat,
            )
        return make_elastic_train_step(
            self._module,
            self._loss_fn,
            self._optimizer,
            mesh,
            precision=self._precision,
            accum_steps=self._accum_steps,
            state_specs=state_specs,
            remat=self._remat,
        )

    def _acquire_step_fn(self):
        """Install the train step for the current mesh, reusing a cached
        executable when this (mesh, step-config) was seen before.
        Returns True on a cache hit. The cached callable is the SAME
        jitted object as last time, so a repeat establish at a
        previously-seen world size dispatches straight through jax's
        aval cache — no retrace, no recompile; a changed batch shape
        (e.g. a different minibatch padding) still misses that aval
        cache and compiles correctly instead of reusing a stale
        executable."""
        key = (
            compile_plane.mesh_signature(self._mesh),
            self._step_config_signature(self._state_specs),
        )
        entry = (
            self._exec_cache.get(key)
            if self.compile_cache_enabled
            else None
        )
        hit = entry is not None
        if entry is None:
            step = self._build_step_fn(self._mesh, self._state_specs)
            if self.compile_cache_enabled:
                entry = self._exec_cache.put(key, step)
            else:
                self._step_entry = None
                self._step_fn = step
                return False
        self._step_entry = entry
        self._step_fn = entry.step_fn
        return hit

    def _step_callable_for(self, args):
        """An AOT-compiled executable exactly matching this call's
        signature (a speculative compile that landed), else the jitted
        step. The choice is memoized per batch signature: the full-args
        signature walks the whole TrainState pytree, which must not
        happen on every hot-loop step — the state/weights/rng shapes
        are fixed for the entry's lifetime, so the (cheap, few-leaf)
        batch part keys the decision."""
        entry = self._step_entry
        if entry is None or not entry.aot:
            return self._step_fn
        batch_sig = compile_plane.args_signature(args[1:3])
        fn = entry.dispatch_memo.get(batch_sig)
        if fn is None:
            compiled = entry.aot.get(compile_plane.args_signature(args))
            fn = compiled if compiled is not None else self._step_fn
            entry.dispatch_memo[batch_sig] = fn
        return fn

    def _world_mesh_for(self, n_devices, axes=None):
        """Hypothetical mesh over the first ``n_devices`` visible
        devices (same layout rule as :func:`build_world_mesh`), or None
        when that size cannot materialize on this backend. This is the
        speculation target and bounds what speculation can reach:
        shrink/re-grow sizes within the visible device set compile
        (exactly for single-backend resizes; as a persistent-cache warm
        across a cross-host re-form), while a GROWTH past the visible
        set returns None and the hint is dropped — no backend can
        compile for devices it cannot see (docs/compile_plane.md).

        ``axes`` overrides the layout hook — a layout-hinted
        speculation targets the SOLVER's candidate layout for that
        world, not whatever the hook would answer today.

        Runs on the speculative compiler's daemon thread against a live
        established backend, but the device enumeration still goes
        through the escapable probe with a hard timeout (edlint R1): a
        transport that wedges mid-steady-state must fail this
        background compile, not park it forever."""
        devices = np.asarray(escapable_call(jax.devices, timeout=30.0))
        n_devices = int(n_devices)
        if n_devices <= 0 or n_devices > devices.size:
            return None
        sub = devices[:n_devices]
        if axes is None:
            axes = (
                self._mesh_axes_fn(n_devices)
                if self._mesh_axes_fn
                else None
            )
        if not axes:
            return Mesh(sub, ("data",))
        names = tuple(axes)
        sizes = tuple(int(axes[n]) for n in names)
        if int(np.prod(sizes)) != n_devices:
            return None
        return Mesh(sub.reshape(sizes), names)

    def _abstract_step_args(
        self, mesh, example, state_specs=None, state_abstract=None
    ):
        """ShapeDtypeStruct argument tuple for AOT-lowering the step on
        ``mesh`` — shapes exactly as :meth:`train_step` will place them
        (padded rows derive from the worker's fixed minibatch).

        The replicated plane passes neither optional: the live state's
        shapes with replicated shardings. A layout-hinted speculation
        passes BOTH — the hypothetical layout's spec tree and padded
        abstract state — so the lowered signature carries each leaf's
        NamedSharding exactly as the future establish will place it."""
        features, labels = example
        # shape metadata only — no host materialization of the leaf
        leaf0 = jax.tree_util.tree_leaves(features)[0]
        mb = self.default_minibatch_size or int(leaf0.shape[0])
        rows = self.local_rows(mb)
        n_proc = self._spec.num_processes if self._spec else 1
        g_rows = rows * n_proc
        # weights/epochs carry one row per LOCAL device per process —
        # on a real world that equals the mesh size; on a hypothetical
        # subset mesh (speculation on a single backend) the placement
        # keeps the local extent, so the signature must too
        w_rows = jax.local_device_count() * n_proc
        row_axes = row_partition_spec(mesh)[0]

        def batch_abs(x):
            x = np.asarray(x)
            spec = P(*((row_axes,) + (None,) * (x.ndim - 1)))
            return jax.ShapeDtypeStruct(
                (g_rows,) + x.shape[1:],
                x.dtype,
                sharding=NamedSharding(mesh, spec),
            )

        def state_abs(leaf, spec=None):
            return jax.ShapeDtypeStruct(
                tuple(leaf.shape),
                leaf.dtype,
                sharding=NamedSharding(
                    mesh, spec if spec is not None else P()
                ),
            )

        state_src = (
            state_abstract if state_abstract is not None else self._ts
        )
        if state_specs is None:
            state_tree = jax.tree_util.tree_map(state_abs, state_src)
        else:
            state_tree = jax.tree_util.tree_map(
                state_abs, state_src, state_specs
            )
        row_shard = NamedSharding(mesh, P(row_axes))
        return (
            state_tree,
            jax.tree_util.tree_map(batch_abs, features),
            jax.tree_util.tree_map(batch_abs, labels),
            jax.ShapeDtypeStruct(
                (w_rows,), np.float32, sharding=row_shard
            ),
            jax.ShapeDtypeStruct((w_rows,), np.int32, sharding=row_shard),
            jax.random.PRNGKey(0),
        )

    def _speculative_compile(self, hint):
        """SpeculativeCompiler's compile_fn: build + AOT-compile the
        step for a hypothetical world and park it in the executable
        cache. Returns False (-> counted dropped) for candidates that
        cannot materialize.

        ``hint`` is either a bare device count (the replicated plane's
        historic form) or a ``(n_devices, axes_items)`` tuple from
        :meth:`_layout_hints` — a solver candidate layout for that
        world. Bare-size hints on sharded planes stay skipped (their
        spec/padding trees are world-specific establish-time state,
        and multi-process re-forms tear the backend down regardless —
        the persistent cache is their amortization layer). LAYOUT
        hints on the pjit dense plane are the exception that motivated
        this PR: a single-backend resize survives the membership
        change, so a pre-compiled (mesh, specs) entry turns the next
        planned layout change into pure state movement."""
        axes = None
        if isinstance(hint, tuple):
            n_devices, axes = int(hint[0]), dict(hint[1])
        else:
            n_devices = int(hint)
        if not self.compile_cache_enabled:
            return False
        if axes is None and self.is_sharded:
            return False
        if axes is not None and not (
            self._pjit_dense and self._builder is not None
        ):
            return False
        example = self._spec_example or self._last_local
        if example is None or self._ts is None:
            return False
        mesh = self._world_mesh_for(n_devices, axes=axes)
        if mesh is None:
            return False
        if axes is None:
            state_specs = None
            state_abstract = None
        else:
            _, param_specs = self._builder(mesh)
            sharded = collect_sharded_paths(param_specs)
            abstract = self._abstract_ts(example)
            state_specs = build_state_specs(abstract, sharded)
            state_abstract = self._padded_abstract_for(
                mesh, abstract, state_specs
            )
            if not self._specs_fit_mesh(mesh, state_abstract, state_specs):
                return False  # layout the shards reject: drop the hint
        key = (
            compile_plane.mesh_signature(mesh),
            self._step_config_signature(state_specs),
        )
        if self._exec_cache.get(key, count=False) is not None:
            return True  # already built (idempotent hint)
        step = self._build_step_fn(mesh, state_specs)
        entry = self._exec_cache.put(key, step, speculative=True)
        compile_plane.aot_compile(
            entry,
            self._abstract_step_args(
                mesh,
                example,
                state_specs=state_specs,
                state_abstract=state_abstract,
            ),
            stats=self._exec_cache.stats,
        )
        return True

    @staticmethod
    def _specs_fit_mesh(mesh, abstract_ts, state_specs):
        """Quiet feasibility probe for a HYPOTHETICAL layout: every
        sharded dim must divide its mesh axis. The establish-path twin
        (:meth:`_check_shard_divisibility`) raises with operator
        guidance; a speculation just drops the candidate."""
        ok = [True]

        def check(leaf, spec):
            for dim, axis_name in enumerate(spec or ()):
                if axis_name is None:
                    continue
                if leaf.shape[dim] % int(mesh.shape[axis_name]):
                    ok[0] = False

        jax.tree_util.tree_map(check, abstract_ts, state_specs)
        return ok[0]

    def _start_speculative_compiler(self):
        if not (self.speculative_compile and self.compile_cache_enabled):
            return
        sc = compile_plane.SpeculativeCompiler(
            self._speculative_compile, stats=self._exec_cache.stats
        )
        sc.start()
        self._spec_compiler = sc
        # default hints: one process joining or leaving the current
        # world; the worker layers membership-service hints on top.
        # With a layout planner the CURRENT size hints too — its top-2
        # covers the next-best layout at this size, so a planned
        # same-size layout change (e.g. a budget-driven dp/tp shift)
        # finds its executable pre-built
        n_dev = self._mesh.devices.size
        n_proc = self._spec.num_processes if self._spec else 1
        per_proc = max(1, n_dev // max(1, n_proc))
        sizes = [n_dev - per_proc, n_dev + per_proc]
        if self._planner is not None and self._pjit_dense:
            sizes.append(n_dev)
        self.hint_world_sizes(sizes)

    def hint_world_sizes(self, device_counts):
        """Feed likely next world sizes (in DEVICES) to the speculative
        compiler; non-blocking, deduplicated, no-op when speculation is
        off. With a layout planner, each size expands to the solver's
        top-2 (world, layout) candidates — the layout-hinted
        speculation of the ISSUE-20 tentpole."""
        if self._spec_compiler is None:
            return
        hints = []
        for n in device_counts:
            n = int(n)
            expanded = self._layout_hints(n)
            hints.extend(expanded if expanded else [n])
        self._spec_compiler.hint(hints)

    def _layout_hints(self, n_devices):
        """Solver candidates for ``n_devices`` as hashable
        ``(n, axes_items)`` hint tuples (empty without a planner /
        profile / pjit plane — the bare size is the hint then)."""
        if self._planner is None or not self._pjit_dense:
            return []
        if n_devices <= 0:
            return []
        try:
            layouts = self._planner.candidates(n_devices, top=2)
        except Exception:
            logger.debug(
                "layout candidate enumeration failed for %d devices",
                n_devices,
                exc_info=True,
            )
            return []
        return [
            (
                n_devices,
                tuple(layout_solver.mesh_axes_for(lay).items()),
            )
            for lay in layouts
        ]

    def _shutdown_compile_helpers(self):
        sc, self._spec_compiler = self._spec_compiler, None
        if sc is not None:
            sc.shutdown()
        feeder, self._feeder = self._feeder, None
        if feeder is not None:
            feeder.shutdown()

    def close(self):
        """Release compile-plane helper threads (idempotent; the worker
        calls it at teardown, tests at fixture exit)."""
        self._shutdown_compile_helpers()

    # -- step overlap: async H2D staging + deferred metric fetches ---------

    def _place_local_pair(self, features, labels, rows):
        local = (
            self._pad_local(features, rows),
            self._pad_local(labels, rows),
        )
        return (
            local,
            self._place_batch(local[0]),
            self._place_batch(local[1]),
        )

    def stage_next(self, features, labels, minibatch_size):
        """Start placing a batch onto the mesh on the feeder thread; a
        later :meth:`train_step` with the same (features, labels)
        objects picks the placement up instead of re-placing inline.
        Call right before a blocking sync step so H2D overlaps the
        fetch."""
        if features is None or self._mesh is None:
            return
        if self._feeder is None:
            self._feeder = _BatchFeeder(self._place_local_pair)
        rows = self.local_rows(minibatch_size)
        self._feeder.stage(
            (id(features), id(labels)), (features, labels, rows)
        )

    def _take_staged(self, features, labels):
        if self._feeder is None:
            return None
        return self._feeder.take(
            (id(features), id(labels)), should_abort=self.abort_check
        )

    def drain_metrics(self):
        """Host floats of every deferred (unsynced) step loss, oldest
        first — the collect-later half of dispatch-and-collect-later.
        Call at log/eval/sync boundaries. On a wedged device or a
        failed collective the pending scalars are dropped (their steps'
        accounting is handled by the failed-window path)."""
        pending, self._pending_metrics = self._pending_metrics, []
        self._pending_metrics_overflowed = False
        if not pending or self._wedged:
            return []
        out = []
        try:
            for loss in pending:
                out.append(
                    loss if isinstance(loss, float) else float(loss)
                )
        except Exception:
            logger.warning(
                "deferred loss fetch failed (broken collective?); "
                "dropping %d pending metrics",
                len(pending) - len(out),
                exc_info=True,
            )
        return out

    def _leaf_is_paddable(self, names):
        return any(
            spec_path_matches(spec_path, names)
            for spec_path in self._paddable_spec_paths
        )

    def _padded_abstract_for(
        self, mesh, abstract, state_specs, record=False
    ):
        """Placement shapes of ``abstract`` on ``mesh``: PadDim0-marked
        sharded leaves whose dim 0 doesn't divide round UP; everything
        else passes through. ``record=True`` replaces
        ``_logical_dim0`` (padding is a per-world property) — establish
        only; a layout-hinted speculation computes a HYPOTHETICAL
        world's padding on the daemon thread and must not mutate the
        live trainer's map."""
        from elasticdl_tpu.common.pytree import key_path_names

        logical = {}
        axes = {
            name: int(mesh.shape[name]) for name in mesh.axis_names
        }

        def pad(key_path, leaf, spec):
            if not _is_sharded_spec(spec):
                return leaf
            names = tuple(key_path_names(key_path))
            pad0 = padded_dim0(leaf.shape[0], spec, axes)
            if pad0 == leaf.shape[0] or not self._leaf_is_paddable(
                names
            ):
                return leaf
            logical[names] = int(leaf.shape[0])
            return jax.ShapeDtypeStruct(
                (pad0,) + tuple(leaf.shape[1:]), leaf.dtype
            )

        padded = jax.tree_util.tree_map_with_path(
            pad, abstract, state_specs
        )
        if record:
            self._logical_dim0 = logical
        return padded

    def _pad_abstract(self, abstract):
        """This world's placement shapes (recorded in
        ``_logical_dim0``); see :meth:`_padded_abstract_for`."""
        return self._padded_abstract_for(
            self._mesh, abstract, self._state_specs, record=True
        )

    def _pad_tree_values(self, tree, padded_abstract):
        """Zero-pad host values up to this world's placement shapes."""

        def pad(x, leaf):
            x = np.asarray(x)
            if x.shape == tuple(leaf.shape):
                return x
            out = np.zeros(tuple(leaf.shape), x.dtype)
            out[: x.shape[0]] = x
            return out

        return jax.tree_util.tree_map(pad, tree, padded_abstract)

    def logical_dim0_by_path(self):
        """{'a/b/c': true dim0} for this world's padded leaves — the
        checkpoint manager records these so host-side consumers
        (export, host-twin scoring) clip the padding back off."""
        return {
            "/".join(names): v
            for names, v in self._logical_dim0.items()
        }

    def _establish_sharded(self, example_batch):
        """Place sharded-parameter state: the in-memory replica plane
        first (no disk in the path — see ShardMirror), then the newest
        restorable checkpoint (falling back through older complete ones
        — a killed rank can leave the newest version torn), then a
        second replica attempt (a torn newer checkpoint must not beat a
        healthy mirror), else deterministic re-init."""
        from elasticdl_tpu.common.sharded_checkpoint import load_sharded

        if example_batch is None and self._last_local is None:
            raise ValueError("first establish() needs an example batch")
        example = example_batch or self._last_local
        # abstract shapes, not a real init: spec building only needs the
        # treedef, and a full host materialization of every (V,D) table
        # on every process at every re-form is exactly the memory spike
        # vocab-sharding exists to avoid
        abstract = self._abstract_ts(example)
        self._state_specs = build_state_specs(
            abstract, self._sharded_paths
        )
        # PadDim0-marked leaves whose dim 0 doesn't divide THIS world
        # get zero-padded placement shapes (recorded in _logical_dim0);
        # everything downstream — placement, mirrors, restore targets —
        # works in this world's padded space, while checkpoints and the
        # plan math stay anchored to the logical rows
        padded = self._pad_abstract(abstract)
        self._check_shard_divisibility(padded)
        candidates = (
            self.restore_provider() if self.restore_provider else None
        ) or []
        if isinstance(candidates, str):
            candidates = [candidates]
        was_live = self._host_step > 0
        old_ts, self._ts = self._ts, None
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s), self._state_specs
        )
        floor = _max_checkpoint_version(candidates)
        if (
            self._pjit_dense
            and old_ts is not None
            and self._placed_epoch == distributed.backend_epoch()
        ):
            # layout re-solve on resize (ElasWave-style, PAPERS.md
            # 2510.00606): the backend survived this membership change
            # (single-backend resize), so the state moves DIRECTLY from
            # the old placement to the new NamedShardings — the runtime
            # relays buffers device-to-device, no host round trip, no
            # disk. When the backend was torn down (a multi-process
            # re-form), the old buffers are gone and the snapshot
            # interchange below (sharded checkpoints) is the path.
            try:
                with profiling.annotate("elastic/resize/relayout"):

                    def move(target, leaf, sharding):
                        t_shape = tuple(target.shape)
                        if tuple(leaf.shape) != t_shape:
                            # a PadDim0 leaf whose padded extent
                            # differs between the two worlds: repad in
                            # DEVICE space (slice the old world's inert
                            # rows off / append zero rows) before the
                            # relayout put. Rows past the logical
                            # extent are zeros by construction, so the
                            # move stays bitwise on the logical rows.
                            if tuple(leaf.shape[1:]) != t_shape[1:]:
                                raise ValueError(
                                    "relayout shape mismatch beyond "
                                    "dim 0: %r -> %r"
                                    % (tuple(leaf.shape), t_shape)
                                )
                            t0, o0 = t_shape[0], leaf.shape[0]
                            if t0 < o0:
                                leaf = leaf[:t0]
                            else:
                                leaf = jnp.concatenate(
                                    [
                                        leaf,
                                        jnp.zeros(
                                            (t0 - o0,) + t_shape[1:],
                                            leaf.dtype,
                                        ),
                                    ],
                                    axis=0,
                                )
                        return jax.device_put(leaf, sharding)

                    self._ts = jax.tree_util.tree_map(
                        move, padded, old_ts, shardings
                    )
                logger.info(
                    "pjit dense plane re-laid out onto the new mesh "
                    "(old -> new NamedShardings, state moved in place)"
                )
                return
            except Exception:
                self._ts = None
                logger.warning(
                    "direct layout re-solve failed; falling back to "
                    "the snapshot interchange",
                    exc_info=True,
                )
        # COLLECTIVE attempts: mirror_enabled() answers from the job
        # args, so every rank takes the same branch; all further
        # decisions inside derive from the all-gathered summary
        if self.mirror_enabled():
            try:
                if self._try_assemble_from_mirrors(
                    abstract, floor, allow_stale=False
                ):
                    return
            except Exception:
                logger.warning(
                    "replica-plane assembly failed; falling back to "
                    "checkpoints",
                    exc_info=True,
                )
        # EVERY PadDim0 leaf restores into THIS world's placement shape
        # (padded, or the logical rows when this world divides): the
        # stored checkpoint may carry a DIFFERENT world's padding, and
        # rows past the logical extent are zeros either way. Keying on
        # currently-padded leaves alone would let a padded-world
        # checkpoint restore at its stored padded shape into a
        # divisible world — desynchronizing the state from the specs.
        from elasticdl_tpu.common.pytree import key_path_names

        target_shapes = {}

        def _collect_target(key_path, leaf, spec):
            names = tuple(key_path_names(key_path))
            if _is_sharded_spec(spec) and self._leaf_is_paddable(names):
                target_shapes["/".join(names)] = tuple(leaf.shape)

        jax.tree_util.tree_map_with_path(
            _collect_target, padded, self._state_specs
        )
        for restore_dir in candidates:
            try:
                version, self._ts = load_sharded(
                    restore_dir,
                    shardings,
                    target_shapes=target_shapes or None,
                )
                logger.info(
                    "sharded state restored at v%d from %s",
                    version,
                    restore_dir,
                )
                if floor > version:
                    # a torn NEWER directory exists (killed rank):
                    # future saves must number past it, or its stale
                    # manifests would merge into later restores. The
                    # scalar is committed onto the mesh like every other
                    # leaf (a host-local scalar inside an otherwise
                    # mesh-global TrainState breaks multi-host jit).
                    self._ts = self._ts.replace(
                        version=place_from_host_specs(
                            self._mesh, np.int32(floor), P()
                        )
                    )
                break
            except Exception:
                logger.warning(
                    "sharded checkpoint %s unrestorable onto the new "
                    "mesh; trying older",
                    restore_dir,
                    exc_info=True,
                )
        if self._ts is None and self.mirror_enabled():
            # second attempt, stale allowed: every checkpoint candidate
            # proved unrestorable, so an older-than-floor mirror is
            # still the best recoverable state (all ranks reach this
            # point together — the checkpoint loop reads the same
            # shared directory)
            try:
                self._try_assemble_from_mirrors(
                    abstract, floor, allow_stale=True
                )
            except Exception:
                logger.warning(
                    "stale replica-plane assembly failed",
                    exc_info=True,
                )
        if self._ts is None:
            if was_live:
                logger.warning(
                    "membership change with sharded parameters and no "
                    "restorable checkpoint: state RE-INITIALIZED "
                    "(enable --checkpoint_steps to bound this loss)"
                )
            init_ts = self._pad_tree_values(
                self._host_init_ts(example), padded
            )
            # version continuity: re-initialized state must start PAST
            # any existing checkpoint version, or future saves would
            # reuse an old ckpt_vN directory whose stale manifests (from
            # a departed rank / larger world) would silently merge into
            # restores
            if floor:
                init_ts = init_ts.replace(
                    version=np.int32(floor)
                )
            self._ts = place_from_host_specs(
                self._mesh, init_ts, self._state_specs
            )

    # -- in-memory replica plane (no-disk recovery) -------------------------

    def _world_axes(self, n_devices):
        """Mesh layout for an arbitrary world size: the zoo hook's
        answer, else the flat 1-axis data layout. Deterministic, so
        every rank (and every FUTURE world reasoning about a PAST
        world's blocks) computes the same layout."""
        axes = (
            self._mesh_axes_fn(n_devices) if self._mesh_axes_fn else None
        )
        return dict(axes) if axes else {"data": int(n_devices)}

    def mirror_enabled(self):
        """True when the replica plane is on (sharded job + cadence set).
        The flag comes from the job args, so it is GLOBAL: every rank
        answers identically, which the collective call sites rely on."""
        return bool(self.mirror_steps) and self.is_sharded

    def maybe_refresh_mirror(self, version):
        """Cadence wrapper; call at rank-aligned sync indices only.

        ``version`` is the aligned step version (identical on every
        rank), and ``_last_mirror_version`` is set by the collective
        refresh itself (identical on every rank after establish's
        refresh), so the predicate is global — no rank can sit out the
        ppermute."""
        if not self.mirror_enabled() or self._ts is None:
            return False
        # gate on the VERSION MARKER alone, never on _mirror presence:
        # the marker is aligned across ranks by construction (set by
        # every establish-tail attempt, success or failure), while
        # _mirror presence diverges — a joiner has none, survivors keep
        # stale ones — and a presence-gated predicate would send the
        # joiner into the collective ppermute alone
        if version - self._last_mirror_version < self.mirror_steps:
            return False
        self.refresh_mirror()
        return True

    def _split_by_sharding(self):
        """(sharded {path: global leaf}, {path: spec}, replicated host
        pytree with int8 placeholders at the sharded leaves)."""
        from elasticdl_tpu.common.pytree import key_path_names

        sharded, specs = {}, {}

        def pick(key_path, leaf, spec):
            names = tuple(key_path_names(key_path))
            if _is_sharded_spec(spec):
                sharded[names] = leaf
                specs[names] = spec
                return np.zeros((), np.int8)
            if hasattr(leaf, "addressable_shards"):
                return np.asarray(leaf.addressable_shards[0].data)
            return np.asarray(leaf)

        replicated = jax.tree_util.tree_map_with_path(
            pick, self._ts, self._state_specs
        )
        return sharded, specs, replicated

    def refresh_mirror(self):
        """Capture a :class:`ShardMirror` — COLLECTIVE: every rank must
        call at the same aligned step (periodic cadence, the consensus
        pause, or establish's tail). One jitted ppermute ships each
        sharded leaf's process block to the next process over ICI; the
        host staging afterwards is local-only."""
        if self._ts is None or not self._sharded_paths:
            return
        # replicated-leaf host fetches are device interactions too
        sharded, specs, replicated = self._escapable(
            self._split_by_sharding
        )
        if not sharded:
            return
        n_dev = self._mesh.devices.size
        n_local = jax.local_device_count()
        flat_axes = row_partition_spec(self._mesh)[0]
        if self._mirror_perm_fn is None:
            spec_tree = {p: specs[p] for p in sharded}
            # shift by n_local devices = one PROCESS: the whole process
            # block lands on the next process (a one-device shift would
            # leave most of a multi-device process's rows on itself)
            perm = [(d, (d + n_local) % n_dev) for d in range(n_dev)]

            def body(tree):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.ppermute(x, flat_axes, perm), tree
                )

            self._mirror_perm_fn = jax.jit(
                shard_map(
                    body,
                    mesh=self._mesh,
                    in_specs=(spec_tree,),
                    out_specs=spec_tree,
                    check_rep=False,
                )
            )
        # the permute dispatch AND the host fetches are escapable: a
        # peer death racing the refresh must not wedge this rank
        def _permute_and_stage():
            with self._mesh:
                permuted = self._mirror_perm_fn(sharded)
            version = int(host_copy(self._ts.version))
            own, replica = {}, {}
            for path, leaf in sharded.items():
                own[path], _ = _local_block(leaf)
                replica[path], _ = _local_block(permuted[path])
            return version, own, replica

        version, own, replica = self._escapable(_permute_and_stage)
        n_proc = self._spec.num_processes if self._spec else 1
        old_pid = self._spec.process_id if self._spec else 0
        self._mirror = ShardMirror(
            version, n_proc, old_pid, own, replica, replicated
        )
        self._last_mirror_version = version
        logger.info(
            "replica plane refreshed at v%d (pid %d/%d)",
            version,
            old_pid,
            n_proc,
        )

    def _all_gather_process_row(self, row):
        """All-gather one small int32 row per process (device slot 0
        carries it). COLLECTIVE: every rank must call with the same row
        width. Returns [tuple(ints)] indexed by process — identical on
        every rank, so decisions derived from it are global."""
        n_dev = self._mesh.devices.size
        n_local = jax.local_device_count()
        n_proc = self._spec.num_processes
        flat_axes = row_partition_spec(self._mesh)[0]
        row = np.asarray(row, np.int32)
        local = np.zeros((n_local, row.shape[0]), np.int32)
        local[0] = row
        g = jax.make_array_from_process_local_data(
            NamedSharding(self._mesh, P(flat_axes, None)),
            local,
            (n_dev, row.shape[0]),
        )
        gather = self._gather_fns.get(row.shape[0])
        if gather is None:
            # cached per (mesh, row width): the in-plane eval consensus
            # calls this once per aligned sync — a fresh lambda each
            # call would retrace/recompile every time
            gather = jax.jit(
                shard_map(
                    lambda x: jax.lax.all_gather(
                        x, flat_axes, tiled=True
                    ),
                    mesh=self._mesh,
                    in_specs=(P(flat_axes, None),),
                    out_specs=P(None, None),
                    check_rep=False,
                )
            )
            self._gather_fns[row.shape[0]] = gather
        with self._mesh:
            out = gather(g)
        table = np.asarray(out.addressable_shards[0].data)
        return [
            tuple(int(v) for v in table[p * n_local])
            for p in range(n_proc)
        ]

    def eval_have_consensus(self, have):
        """COLLECTIVE: total count of ranks reporting pending eval work.

        The in-plane eval protocol's loop condition — every rank calls
        at the same aligned point, ranks with no work participate in
        the forwards with dummy rows until this reaches zero."""
        table = self._escapable(
            lambda: self._all_gather_process_row([1 if have else 0])
        )
        return sum(h for (h,) in table)

    def eval_step(self, features, minibatch_size):
        """COLLECTIVE forward for in-plane evaluation: every rank of
        the mesh participates (the sharded model's lookups/ring are
        collectives), each feeding its own eval rows — ``features=None``
        participates with dummy rows (the previous batch) and discards
        the outputs. Returns this process's output rows as host numpy
        (caller slices to its true row count). Scores the CURRENT
        parameters — no checkpoint, no host twin, no aggregate-table
        materialization anywhere (the table stays sharded in HBM,
        which is the point: reference worker/worker.py:659-693
        evaluates on the training plane the same way)."""
        rows = self.local_rows(minibatch_size)
        if features is None:
            if self._last_local is None:
                raise RuntimeError(
                    "cannot run a dummy eval step before the first data "
                    "step"
                )
            features = self._last_local[0]
        local = self._pad_local(features, rows)
        g = self._place_batch(local)
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_fn()

        def _dispatch():
            with self._mesh:
                out = self._eval_fn(self._ts, g)
            return jax.tree_util.tree_map(
                lambda a: _local_block(a)[0], out
            )

        return self._escapable(_dispatch)

    def _build_eval_fn(self):
        """Jitted shard_map INFERENCE forward over the established mesh
        (training=False: no dropout, no mutable-state updates — the
        same mode every other eval path scores in)."""
        from elasticdl_tpu.nn.model_api import apply_model
        from elasticdl_tpu.training.precision import get_policy

        pol = get_policy(self._precision)
        module = self._module
        ts_spec = (
            self._state_specs if self._state_specs is not None else P()
        )
        row_spec = row_partition_spec(self._mesh)
        if self._pjit_dense:
            # global-semantics forward: XLA partitions per the params'
            # NamedShardings (same GSPMD discipline as the train step);
            # the row-sharded out-sharding keeps each process's output
            # rows on its own devices for the _local_block consumer
            def global_fwd(ts, features):
                params, state = ts.params, ts.state
                if pol is not None:
                    params = pol.cast_to_compute(params)
                    features = pol.cast_to_compute(features)
                output, _ = apply_model(
                    module, params, state, features, training=False
                )
                if pol is not None:
                    output = pol.cast_output(output)
                return output

            return jax.jit(
                global_fwd,
                out_shardings=NamedSharding(self._mesh, row_spec),
            )

        def per_device(ts, features):
            params, state = ts.params, ts.state
            if pol is not None:
                params = pol.cast_to_compute(params)
                features = pol.cast_to_compute(features)
            output, _ = apply_model(
                module, params, state, features, training=False
            )
            if pol is not None:
                output = pol.cast_output(output)
            return output

        return jax.jit(
            shard_map(
                per_device,
                mesh=self._mesh,
                in_specs=(ts_spec, row_spec),
                out_specs=row_spec,
                check_rep=False,
            )
        )

    def _replicated_source_rank(self):
        """Lowest rank holding live replicated state (the broadcast
        source), or -1 when nobody does. Collective when the world has
        more than one process; local (trivial) otherwise."""
        mine = 1 if self._host_ts is not None else 0
        if self._spec is None or self._spec.num_processes <= 1:
            return 0 if mine else -1
        table = self._all_gather_process_row([mine])
        ranks = [p for p, (has,) in enumerate(table) if has]
        return min(ranks) if ranks else -1

    def _gather_mirror_info(self):
        """All-gather every NEW-world process's mirror summary:
        ``[(has, version, n_old, old_pid)]`` indexed by new rank."""
        row = [0, 0, 0, 0]
        if self._mirror is not None:
            row = [
                1,
                self._mirror.version,
                self._mirror.n_old,
                self._mirror.old_pid,
            ]
        return self._all_gather_process_row(row)

    def _try_assemble_from_mirrors(self, abstract, floor, allow_stale):
        """Rebuild the full TrainState from surviving mirrors — no disk.

        COLLECTIVE: every rank of the new world must call with the same
        arguments; all decisions derive from the all-gathered summary so
        ranks cannot diverge. Returns True when ``self._ts`` was set.
        ``allow_stale=False`` refuses when a checkpoint directory is
        newer than the mirrors (first attempt; the checkpoint loop runs,
        then a second attempt with True catches torn checkpoints)."""
        from elasticdl_tpu.common.pytree import key_path_names

        info = self._gather_mirror_info()
        n_local = jax.local_device_count()

        # sharded leaf metadata from the abstract state (joiners need
        # shapes/dtypes/specs without holding any data)
        meta = {}  # path -> (shape, dtype, spec)

        def collect(key_path, leaf, spec):
            if _is_sharded_spec(spec):
                names = tuple(key_path_names(key_path))
                meta[names] = (tuple(leaf.shape), leaf.dtype, spec)

        jax.tree_util.tree_map_with_path(
            collect, abstract, self._state_specs
        )

        # the OLD world's mesh layout is reconstructible from its
        # process count alone (the zoo hook is deterministic), so every
        # new rank — joiners included — computes identical old blocks.
        # Blocks live in each world's PADDED space (pad == logical for
        # divisible worlds) and are CLIPPED to the logical rows: the
        # pad rows are zeros by construction, so the plan only ever
        # moves real rows, whatever padding either world used.
        def clipped_block(axes, spec, shape0, pid):
            pad0 = padded_dim0(shape0, spec, axes)
            lo, hi = process_dim0_block(
                axes, spec, pad0, n_local, pid
            )
            return lo, min(hi, int(shape0))

        n_olds = {n for has, v, n, _ in info if has}
        old_blocks_by_n = {}
        old_bases_by_n = {}  # UNCLIPPED lo (slicing into mirror arrays)
        for n in n_olds:
            try:
                old_axes = self._world_axes(n * n_local)
            except Exception:
                logger.warning(
                    "old world of %d processes does not fit the mesh "
                    "layout hook; its mirrors are unusable", n,
                    exc_info=True,
                )
                continue
            old_blocks_by_n[n] = {
                path: (
                    lambda pid, _axes=old_axes, _spec=spec, _s0=shape[0]:
                    clipped_block(_axes, _spec, _s0, pid)
                )
                for path, (shape, _, spec) in meta.items()
            }
            old_bases_by_n[n] = {
                path: (
                    lambda pid, _axes=old_axes, _spec=spec, _s0=shape[0]:
                    process_dim0_block(
                        _axes,
                        _spec,
                        padded_dim0(_s0, _spec, _axes),
                        n_local,
                        pid,
                    )[0]
                )
                for path, (shape, _, spec) in meta.items()
            }
        leaf_spans = {
            path: shape[0] for path, (shape, _, _) in meta.items()
        }
        plan = None
        # plan against the newest version whose old layout resolved
        # (rows whose n_old failed to resolve never equal a dict key,
        # so the per-n filter alone excludes them)
        for n, leaf_blocks in old_blocks_by_n.items():
            cand = plan_mirror_ranges(
                [
                    row if row[2] == n else (0, 0, 0, 0)
                    for row in info
                ],
                leaf_blocks,
                leaf_spans,
                floor,
                allow_stale,
            )
            if cand is not None and (
                plan is None or cand[0] > plan[0]
            ):
                plan = cand
        if plan is None:
            if any(has for has, _, _, _ in info):
                logger.warning(
                    "replica plane cannot cover the old world (gap or "
                    "stale mirrors) — falling back to checkpoints"
                )
            return False
        target_v, n_old, assignments = plan

        n_proc_new = self._spec.num_processes
        n_dev = self._mesh.devices.size
        me = self._spec.process_id
        new_axes = {
            name: int(self._mesh.shape[name])
            for name in self._mesh.axis_names
        }
        flat_axes = row_partition_spec(self._mesh)[0]

        # my contributions: the plan's pieces assigned to my new rank,
        # sliced out of my mirror's own/replica arrays
        m = self._mirror
        my_old_pid = m.old_pid if m is not None else -1

        old_bases = old_bases_by_n[n_old]

        def my_piece(path, lo, hi, kind):
            # base = the UNCLIPPED start of the source block (the mirror
            # arrays include any old-world pad rows)
            if kind == 0:
                base = old_bases[path](my_old_pid)
                return m.own[path][lo - base : hi - base]
            base = old_bases[path]((my_old_pid - 1) % n_old)
            return m.replica[path][lo - base : hi - base]

        psum_specs = {
            path: P(flat_axes, *([None] * len(shape)))
            for path, (shape, _, _) in meta.items()
        }
        exchange = jax.jit(
            shard_map(
                lambda tree: jax.tree_util.tree_map(
                    lambda x: jax.lax.psum(x, flat_axes), tree
                ),
                mesh=self._mesh,
                in_specs=(psum_specs,),
                out_specs={
                    path: P(*([None] * (len(shape) + 1)))
                    for path, (shape, _, _) in meta.items()
                },
                check_rep=False,
            )
        )

        my_shards = {}
        for r in range(n_proc_new):
            bufs = {}
            for path, (shape, dtype, spec) in meta.items():
                # the new rank's block in THIS world's padded space
                # (plan pieces are clipped to the logical rows, so the
                # buffer's pad tail simply stays zero)
                new_pad0 = padded_dim0(shape[0], spec, new_axes)
                r_lo, r_hi = process_dim0_block(
                    new_axes, spec, new_pad0, n_local, r
                )
                # device slot 0 carries the process contribution; the
                # other local slots stay zero so the psum over devices
                # is an exact sum over processes
                buf = np.zeros(
                    (n_local, r_hi - r_lo) + tuple(shape[1:]), dtype
                )
                for lo, hi, src, kind in assignments[path]:
                    s, e = max(lo, r_lo), min(hi, r_hi)
                    if s < e and src == me:
                        piece = my_piece(path, lo, hi, kind)
                        buf[0, s - r_lo : e - r_lo] = piece[
                            s - lo : e - lo
                        ]
                bufs[path] = buf
            placed = {
                path: jax.make_array_from_process_local_data(
                    NamedSharding(self._mesh, psum_specs[path]),
                    buf,
                    (n_dev,) + buf.shape[1:],
                )
                for path, buf in bufs.items()
            }
            with self._mesh:
                out = exchange(placed)
            if r == me:
                my_shards = {
                    path: np.asarray(
                        arr.addressable_shards[0].data
                    )[0]
                    for path, arr in out.items()
                }

        # replicated leaves: the broadcast SOURCE must be a rank the
        # plan knows holds a target_v mirror — blindly using rank 0
        # would adopt its zero stand-ins when rank 0's own refresh
        # failed or it is a joiner, silently zeroing every dense
        # parameter and optimizer slot. Any participant works; pick the
        # lowest rank deterministically (identical plan on every rank).
        source_rank = min(
            src
            for pieces in assignments.values()
            for _, _, src, _ in pieces
        )
        if m is not None and m.version == target_v:
            repl_host = m.replicated
        else:

            def stand_in(key_path, leaf, spec):
                if _is_sharded_spec(spec):
                    return np.zeros((), np.int8)
                return np.zeros(tuple(leaf.shape), leaf.dtype)

            repl_host = jax.tree_util.tree_map_with_path(
                stand_in, abstract, self._state_specs
            )
        repl = broadcast_from_device0(
            self._mesh, repl_host, source_process=source_rank
        )

        def combine(key_path, leaf, spec, broadcasted):
            names = tuple(key_path_names(key_path))
            if _is_sharded_spec(spec):
                local = my_shards[names]
                new_pad0 = padded_dim0(leaf.shape[0], spec, new_axes)
                return jax.make_array_from_process_local_data(
                    NamedSharding(self._mesh, spec),
                    local,
                    (new_pad0,) + tuple(leaf.shape[1:]),
                )
            return broadcasted

        self._ts = jax.tree_util.tree_map_with_path(
            combine, abstract, self._state_specs, repl
        )
        version = max(target_v, floor)
        self._ts = self._ts.replace(
            version=place_from_host_specs(
                self._mesh, np.int32(version), P()
            )
        )
        logger.info(
            "sharded state reassembled from the replica plane at v%d "
            "(no disk; %d source ranks, old world of %d)",
            target_v,
            len({s for p in assignments.values() for _, _, s, _ in p}),
            n_old,
        )
        return True

    def _check_shard_divisibility(self, abstract_ts):
        """Every sharded leaf must split evenly over the NEW world's mesh.

        The elastic world size changes at runtime; a re-form to a
        non-divisor size would otherwise fail at shard_map trace time
        with an opaque error and crash-loop the worker through
        relaunches. Fail once, loudly, with the fix in the message.
        Validates against the spec tree the step will actually use, so
        the check can never disagree with placement."""
        problems = []

        mirror_problems = []

        def check(key_path, leaf, spec):
            from elasticdl_tpu.common.pytree import key_path_names

            for dim, axis_name in enumerate(spec or ()):
                if axis_name is None:
                    continue
                n = self._mesh.shape[axis_name]
                if leaf.shape[dim] % n:
                    problems.append(
                        "%s: dim %d (=%d) %% %d devices != 0"
                        % (
                            "/".join(key_path_names(key_path)),
                            dim,
                            leaf.shape[dim],
                            n,
                        )
                    )
                if dim != 0:
                    # the replica plane's block math (_local_block,
                    # shape[0] // n_proc) assumes leading-dim sharding;
                    # a P(None, 'data') leaf would stage/assemble wrong
                    mirror_problems.append(
                        "/".join(key_path_names(key_path))
                    )

        jax.tree_util.tree_map_with_path(
            check, abstract_ts.params, self._state_specs.params
        )
        if problems:
            raise ValueError(
                "sharded parameters do not divide the %d-device world: "
                "%s. For row tables whose extra rows are inert "
                "(embeddings), mark the spec PadDim0 in the zoo's "
                "param_shardings and the elastic plane pads/reshards "
                "automatically; otherwise pad the sharded dimension "
                "(e.g. vocab_size) to a multiple of every world size "
                "the job can shrink/grow to."
                % (self._mesh.devices.size, "; ".join(problems))
            )
        if mirror_problems and self.mirror_enabled():
            raise ValueError(
                "the replica plane (--replica_refresh_steps) supports "
                "only leading-dim sharded parameters, but these leaves "
                "shard a later dim: %s. Reshape so the sharded axis is "
                "dim 0, or disable the mirror (replica_refresh_steps=0) "
                "to fall back to checkpoint-based recovery."
                % "; ".join(mirror_problems)
            )

    def _place_batch(self, tree):
        n_proc = self._spec.num_processes
        spec = row_partition_spec(self._mesh)

        def place(x):
            x = np.asarray(x)
            global_shape = (x.shape[0] * n_proc,) + x.shape[1:]
            return jax.make_array_from_process_local_data(
                NamedSharding(self._mesh, spec), x, global_shape
            )

        return jax.tree_util.tree_map(place, tree)

    def _pad_local(self, tree, rows):
        def pad(x):
            x = np.asarray(x)
            short = rows - x.shape[0]
            if short <= 0:
                return x[:rows]
            return np.concatenate([x, np.repeat(x[-1:], short, axis=0)])

        return jax.tree_util.tree_map(pad, tree)

    def local_rows(self, minibatch_size):
        """Fixed per-process rows: minibatch padded so each local device
        holds a whole number of microbatches."""
        chunk = jax.local_device_count() * self._accum_steps
        return -(-minibatch_size // chunk) * chunk

    def train_step(
        self, features, labels, minibatch_size, sync=True, epoch_hint=0
    ):
        """One weighted lockstep step; ``features=None`` participates at
        weight 0 (drain mode). Returns (loss, n_active_devices, count)
        where count is this process's true (unpadded) contribution.

        ``epoch_hint`` is this process's last-polled membership epoch;
        the step pmax-es it across members and ``epoch_consensus`` (set
        at sync) exposes the newest epoch ANY member has seen — the
        skew-proof reform/pause trigger.

        ``sync=False`` skips the device->host fetch and returns
        (None, None, count): dispatch stays asynchronous, so the host
        (task RPCs, input pipeline) runs ahead of the device instead of
        stalling a round trip per step — on a multi-host DCN or a
        tunneled dev chip that latency is ~10 ms/step. Unsynced steps
        are validated at the next ``sync=True`` call; a collective
        failure then rolls the snapshot back to the last validated
        state (bounded by the caller's sync cadence)."""
        rows = self.local_rows(minibatch_size)
        has_data = features is not None
        staged = None
        if has_data:
            leaf = jax.tree_util.tree_leaves(features)[0]
            count = int(np.asarray(leaf).shape[0])
            # step overlap: a placement staged via stage_next (padded +
            # placed on the feeder thread while the previous sync step's
            # fetch blocked) is byte-identical to the inline path — same
            # _pad_local/_place_batch code on the same host arrays
            staged = self._take_staged(features, labels)
            if staged is not None:
                local = staged[0]
            else:
                local = (
                    self._pad_local(features, rows),
                    self._pad_local(labels, rows),
                )
            self._last_local = local
        else:
            count = 0
            if self._last_local is None:
                raise RuntimeError(
                    "cannot run a weight-0 step before the first data step"
                )
            local = self._last_local
        n_local = jax.local_device_count()
        # partial batches pad by repeating the last example; weighting the
        # whole process by its true row fraction keeps a 1-row tail batch
        # from contributing a full step's worth of gradient
        w_value = min(1.0, count / rows) if has_data else 0.0
        w_local = np.full((n_local,), w_value, dtype=np.float32)
        row_spec = row_partition_spec(self._mesh)
        if staged is not None:
            g_features, g_labels = staged[1], staged[2]
        else:
            g_features = self._place_batch(local[0])
            g_labels = self._place_batch(local[1])
        g_weights = jax.make_array_from_process_local_data(
            NamedSharding(self._mesh, row_spec),
            w_local,
            (self._mesh.devices.size,),
        )
        g_epochs = jax.make_array_from_process_local_data(
            NamedSharding(self._mesh, row_spec),
            np.full((n_local,), int(epoch_hint), dtype=np.int32),
            (self._mesh.devices.size,),
        )
        self._host_step += 1
        host_step = self._host_step

        def _dispatch():
            # everything device-touching — eager PRNG ops, the jit
            # call, the sync fetches — runs on the sacrificial thread
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self._seed), host_step
            )
            args = (
                self._ts,
                g_features,
                g_labels,
                g_weights,
                g_epochs,
                rng,
            )
            fn = self._step_callable_for(args)
            with self._mesh:
                try:
                    new_ts, loss, n, epoch_seen = fn(*args)
                except (TypeError, ValueError):
                    if fn is self._step_fn:
                        raise
                    # a speculative AOT executable whose signature check
                    # disagreed with the live call: drop it and dispatch
                    # through the jit path (retraces, stays correct)
                    logger.warning(
                        "AOT executable rejected the step call; "
                        "falling back to jit dispatch",
                        exc_info=True,
                    )
                    if self._step_entry is not None:
                        self._step_entry.aot.clear()
                        self._step_entry.dispatch_memo.clear()
                    new_ts, loss, n, epoch_seen = self._step_fn(*args)
            if not sync:
                # collect-later: the loss scalar stays on device (it is
                # already a future); drain_metrics fetches at boundaries
                return new_ts, loss, None, None
            return (
                new_ts,
                float(host_copy(loss)),
                int(host_copy(n)),
                int(host_copy(epoch_seen)),
            )

        new_ts, loss_v, n_v, epoch_seen_v = self._escapable(_dispatch)
        self._ts = new_ts
        if not sync:
            if has_data:
                if len(self._pending_metrics) < 4096:
                    self._pending_metrics.append(loss_v)
                elif not self._pending_metrics_overflowed:
                    # the bound only exists as a leak backstop; a sync
                    # cadence long enough to hit it loses losses, which
                    # must not happen silently
                    self._pending_metrics_overflowed = True
                    logger.warning(
                        "deferred-metric buffer full (4096): losses of "
                        "further unsynced steps are DROPPED until the "
                        "next drain — sync/drain more often to keep "
                        "the loss record complete"
                    )
            return None, None, count
        # the fetch proves every dispatched collective up to here
        # completed; checkpoint that state as the re-form fallback
        self.epoch_consensus = epoch_seen_v
        self._checked_ts = new_ts
        return loss_v, n_v, count

    def _escapable(self, fn):
        """Run a device-touching callable so the host thread can escape
        a wedged backend.

        A peer death can block ANY backend interaction forever in C++ —
        not just fetches: observed stacks show eager op dispatch
        (PRNGKey) and the jit call itself wedging, because the CPU
        collectives backend executes on the calling thread and the
        listening side of a dead gloo socket just waits (only the
        connected side gets a reset error). A blocked host thread
        cannot poll the master, so the fencer kills a healthy rank and
        turns one process failure into two — exactly the adjacent
        double failure the replica plane cannot cover.

        Delegates to :func:`escapable_call` with the worker-provided
        ``abort_check`` probe and NO hard timeout (a first-step compile
        legitimately takes minutes). When the master has already moved
        the world on, the stuck thread is abandoned (left parked in the
        dead gloo op), the trainer marks itself wedged, and WorldBroken
        takes the ordinary failed-step recovery path with this rank's
        host state intact for the replica-plane reassembly."""
        try:
            return escapable_call(fn, should_abort=self.abort_check)
        except EscapeTimeout:
            self._wedged = True
            raise distributed.WorldBroken(
                "world moved on while this rank's device "
                "stream was wedged by a peer loss"
            )

    def validate(self):
        """Force-complete all dispatched work; True if it all succeeded.

        On success the latest state becomes the checked (re-form
        fallback) state; on failure the checked state is left at the
        last validated point.
        """
        if self._ts is None:
            return True
        if self._wedged:
            # a fetch already wedged on this world: touching the device
            # again would block forever — the state is unvalidatable
            return False
        try:
            self._escapable(lambda: host_copy(self._ts.version))
        except Exception:
            logger.warning("validation failed: a dispatched step errored")
            return False
        self._checked_ts = self._ts
        return True

    def snapshot(self):
        """Pull current state to host (the re-form / checkpoint source).

        Falls back to the last fetch-validated state when the newest
        buffers carry a failed collective (unsynced steps roll back).
        Sharded-parameter jobs return None: one process's host copy of a
        sharded leaf would be its shard alone — the sharded checkpoint
        plane (save_sharded / restore on establish) is their snapshot
        mechanism."""
        if self._sharded_paths:
            return None
        if self._wedged:
            # device fetches block forever on a wedged stream; the last
            # validated host snapshot is the only safe source
            return self._host_ts
        if self._ts is not None:
            try:
                self._host_ts = host_copy(self._ts)
                return self._host_ts
            except Exception:
                logger.warning(
                    "latest state poisoned by a failed collective; "
                    "snapshotting the last validated state"
                )
            if self._checked_ts is not None:
                self._host_ts = host_copy(self._checked_ts)
        return self._host_ts

    def host_params(self):
        return self.snapshot().params

    def load_host_state(self, host_ts):
        """Adopt a checkpointed host TrainState before establish()."""
        self._host_ts = host_ts

    def save_sharded(self, directory):
        """Write this process's shards of the train state (no gather)."""
        from elasticdl_tpu.common.sharded_checkpoint import save_sharded

        save_sharded(
            directory,
            self._ts,
            version=self.version,
            logical_dim0=self.logical_dim0_by_path() or None,
        )

    def restore_sharded(self, directory):
        """Replace the established state with a sharded checkpoint,
        materialized straight onto the current mesh placement."""
        from elasticdl_tpu.common.sharded_checkpoint import load_sharded

        shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, self._ts
        )
        # every PadDim0 leaf restores at the CURRENT placement shape
        # (self._ts already carries it) whatever padding the stored
        # checkpoint used — see the same logic in _establish_sharded
        from elasticdl_tpu.common.pytree import key_path_names

        target_shapes = {}

        def _collect(key_path, leaf):
            names = tuple(key_path_names(key_path))
            if self._leaf_is_paddable(names):
                target_shapes["/".join(names)] = tuple(leaf.shape)

        jax.tree_util.tree_map_with_path(_collect, self._ts)
        version, ts = load_sharded(
            directory, shardings, target_shapes=target_shapes or None
        )
        self._ts = ts
        self._checked_ts = ts
        self._host_ts = host_copy(ts)
        logger.info(
            "restored sharded checkpoint v%d from %s", version, directory
        )
        return version

    def leave(self):
        """Snapshot and leave the world (graceful epoch boundary)."""
        # helper threads must not touch the backend once it starts dying
        self._shutdown_compile_helpers()
        try:
            self.snapshot()
        except Exception:
            logger.warning(
                "state snapshot failed; re-form will use the previous one",
                exc_info=True,
            )
        if (
            self._spec is not None
            and self._spec.process_id == 0
            and self._spec.num_processes > 1
            and not self._wedged
        ):
            # the coordination service lives in THIS process: at a
            # synchronized pause every member leaves at once, and a
            # peer whose disconnect RPC races this teardown FATALs in
            # C++ (uncatchable LOG(FATAL) — a clean drain turns into a
            # crash exit). Rank 0 lingers briefly so peers disconnect
            # against a live coordinator first.
            import time as _time

            _time.sleep(1.5)
        distributed.leave_world()
        self._ts = None
        self._checked_ts = None
        self._mesh = None
        self._step_fn = None
        self._step_entry = None
        # pending deferred losses reference the departed world's buffers
        self._pending_metrics = []
