"""Expert parallelism: MoE dispatch over an ``expert`` mesh axis.

The reference has no expert parallelism (SURVEY.md §2.2: absent). This
is the TPU-native form: each device along the ``expert`` axis owns one
(or more) experts' parameters; tokens are gated top-1, packed into
capacity-bounded per-expert buckets, shipped to their expert with
``lax.all_to_all``, transformed, and shipped back — the same explicit
routing fabric as the HBM embedding plane (nn/hbm_embedding.py), which
is exactly the point: on TPU, "expert parallel" and "vocab-sharded
lookup" are the same all_to_all pattern over ICI with different
per-shard compute.

Capacity semantics follow the standard MoE recipe: each expert accepts
at most ``capacity`` tokens per shard per step; overflow tokens bypass
the experts (identity/zero contribution), weighted out by their gate.
Gradients flow through dispatch, experts, combine, and the gate (via the
gate-probability scaling).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.ring_attention import shard_map


def top1_gate(logits):
    """(T, E) gate logits -> (expert_idx (T,), gate_prob (T,))."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    return idx, jnp.take_along_axis(probs, idx[:, None], axis=1)[:, 0]


def moe_apply(expert_fn, expert_params, x, gate_logits, axis_name, capacity):
    """Route tokens to experts over ``axis_name``; call inside shard_map.

    - ``expert_fn(params, x) -> y``: one expert's computation (same
      in/out feature width).
    - ``expert_params``: this device's expert's parameter slice (leading
      dim 1, squeezed internally).
    - ``x``: (T, D) local tokens; ``gate_logits``: (T, E).

    Returns (T, D): gate-weighted expert outputs, overflow tokens zero.
    """
    n_exp = jax.lax.psum(1, axis_name)
    params = jax.tree_util.tree_map(
        lambda p: jnp.squeeze(p, axis=0), expert_params
    )
    t_local, d = x.shape
    cap = min(capacity, t_local)

    expert_idx, gate = top1_gate(gate_logits)

    # position of each token within its expert's bucket (stable order)
    order = jnp.argsort(expert_idx, stable=True)
    sorted_expert = expert_idx[order]
    counts = jnp.bincount(expert_idx, length=n_exp)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(t_local) - starts[sorted_expert]
    ok = pos < cap
    slot = jnp.where(ok, pos, cap)  # overflow -> trash column

    # (E, cap+1, D) send buffer; row e = tokens for expert e
    send = jnp.zeros((n_exp, cap + 1, d), x.dtype)
    send = send.at[sorted_expert, slot].set(x[order])[:, :cap]
    recv = jax.lax.all_to_all(
        send, axis_name, split_axis=0, concat_axis=0, tiled=True
    )  # (E, cap, D): row p = tokens shard p sent to THIS expert

    y = expert_fn(params, recv.reshape(n_exp * cap, d))
    y = y.reshape(n_exp, cap, d)
    back = jax.lax.all_to_all(
        y, axis_name, split_axis=0, concat_axis=0, tiled=True
    )  # (E, cap, D): row e = this shard's tokens back from expert e

    # un-permute; overflow tokens contribute zero
    gathered = jnp.where(
        ok[:, None],
        back[sorted_expert, jnp.where(ok, pos, 0)],
        0.0,
    )
    inv = jnp.argsort(order, stable=True)
    routed = gathered[inv]
    return routed * gate[:, None].astype(x.dtype)


def make_moe_fn(
    mesh, expert_fn, expert_axis="expert", batch_axis=None, capacity_factor=2.0
):
    """Global wrapper: ``(stacked_expert_params, x, gate_logits) -> y``.

    ``stacked_expert_params`` leaves are (E, ...) sharded over
    ``expert_axis``; ``x`` is (T, D) tokens (optionally sharded over
    ``batch_axis``), ``gate_logits`` (T, E) likewise. Capacity per
    expert = ceil(T_local / E) * capacity_factor.
    """

    def _capacity(t_local, n_exp):
        return max(1, int(-(-t_local // n_exp) * capacity_factor))

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(expert_axis), P(batch_axis), P(batch_axis)),
        out_specs=P(batch_axis),
        check_rep=False,
    )
    def _moe(stacked_params, x, gate_logits):
        cap = _capacity(x.shape[0], int(mesh.shape[expert_axis]))
        return moe_apply(
            expert_fn, stacked_params, x, gate_logits, expert_axis, cap
        )

    return _moe


def reference_moe(expert_fn, per_expert_params, x, gate_logits):
    """Dense semantics the routed form must match (tests): every expert
    runs every token, outputs selected by the top-1 gate."""
    idx, gate = top1_gate(gate_logits)
    outs = jnp.stack(
        [expert_fn(p, x) for p in per_expert_params]
    )  # (E, T, D)
    picked = outs[idx, jnp.arange(x.shape[0])]
    return picked * gate[:, None].astype(x.dtype)
