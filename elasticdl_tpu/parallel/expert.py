"""Expert parallelism: MoE dispatch over an ``expert`` mesh axis.

The reference has no expert parallelism (SURVEY.md §2.2: absent). This
is the TPU-native form: each device along the ``expert`` axis owns one
(or more) experts' parameters; tokens are gated top-1, packed into
capacity-bounded per-expert buckets, shipped to their expert with
``lax.all_to_all``, transformed, and shipped back — the same explicit
routing fabric as the HBM embedding plane (nn/hbm_embedding.py), which
is exactly the point: on TPU, "expert parallel" and "vocab-sharded
lookup" are the same all_to_all pattern over ICI with different
per-shard compute.

Capacity semantics follow the standard MoE recipe: each expert accepts
at most ``capacity`` tokens per shard per step; overflow tokens bypass
the experts (identity/zero contribution), weighted out by their gate.
Gradients flow through dispatch, experts, combine, and the gate (via the
gate-probability scaling).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.ring_attention import shard_map


def topk_gate(logits, k):
    """(T, E) gate logits -> (expert_idx (T, k), gate_probs (T, k)).

    For ``k > 1`` the selected probabilities renormalize to sum to 1
    per token (the GShard top-2 recipe)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate, idx = jax.lax.top_k(probs, k)
    if k > 1:
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    return idx, gate


def top1_gate(logits):
    """(T, E) gate logits -> (expert_idx (T,), gate_prob (T,))."""
    idx, gate = topk_gate(logits, 1)
    return idx[:, 0], gate[:, 0]


def load_balancing_loss(gate_logits):
    """Switch-transformer auxiliary loss: ``E * sum_e f_e * P_e``.

    ``f_e`` = fraction of tokens whose top-1 expert is ``e``; ``P_e`` =
    mean router probability for ``e``. Equals 1.0 at perfect balance,
    grows as routing collapses onto few experts. Differentiable through
    ``P_e`` (the ``f_e`` factor is piecewise-constant, as in the paper).
    """
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    e = probs.shape[-1]
    top1 = jnp.argmax(probs, axis=-1)
    f = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return e * jnp.sum(f * p)


def moe_apply(
    expert_fn,
    expert_params,
    x,
    gate_logits,
    axis_name,
    capacity,
    num_selected=1,
):
    """Route tokens to experts over ``axis_name``; call inside shard_map.

    - ``expert_fn(params, x) -> y``: one expert's computation (same
      in/out feature width).
    - ``expert_params``: this device's expert's parameter slice (leading
      dim 1, squeezed internally).
    - ``x``: (T, D) local tokens; ``gate_logits``: (T, E).
    - ``num_selected``: top-k routing. Each (token, choice) pair routes
      as a virtual token through one shared capacity budget, and a
      token's k expert outputs sum gate-weighted — so top-2 costs 2x
      the dispatch of top-1, not a separate code path.

    Returns (T, D): gate-weighted expert outputs, overflow tokens zero.
    """
    n_exp = jax.lax.psum(1, axis_name)
    params = jax.tree_util.tree_map(
        lambda p: jnp.squeeze(p, axis=0), expert_params
    )
    t_local, d = x.shape
    k = num_selected

    idx_tk, gate_tk = topk_gate(gate_logits, k)
    # choice-major virtual tokens: v[j*T + t] = (token t, choice j)
    expert_idx = idx_tk.T.reshape(-1)  # (k*T,)
    gate = gate_tk.T.reshape(-1)
    vx = jnp.tile(x, (k, 1))  # (k*T, D)
    t_virtual = k * t_local
    cap = min(capacity, t_virtual)

    # position of each virtual token within its expert's bucket
    order = jnp.argsort(expert_idx, stable=True)
    sorted_expert = expert_idx[order]
    counts = jnp.bincount(expert_idx, length=n_exp)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(t_virtual) - starts[sorted_expert]
    ok = pos < cap
    slot = jnp.where(ok, pos, cap)  # overflow -> trash column

    # (E, cap+1, D) send buffer; row e = tokens for expert e
    send = jnp.zeros((n_exp, cap + 1, d), x.dtype)
    send = send.at[sorted_expert, slot].set(vx[order])[:, :cap]
    recv = jax.lax.all_to_all(
        send, axis_name, split_axis=0, concat_axis=0, tiled=True
    )  # (E, cap, D): row p = tokens shard p sent to THIS expert

    y = expert_fn(params, recv.reshape(n_exp * cap, d))
    y = y.reshape(n_exp, cap, d)
    back = jax.lax.all_to_all(
        y, axis_name, split_axis=0, concat_axis=0, tiled=True
    )  # (E, cap, D): row e = this shard's tokens back from expert e

    # un-permute; overflow tokens contribute zero
    gathered = jnp.where(
        ok[:, None],
        back[sorted_expert, jnp.where(ok, pos, 0)],
        0.0,
    )
    inv = jnp.argsort(order, stable=True)
    routed = gathered[inv] * gate[:, None].astype(x.dtype)
    return routed.reshape(k, t_local, d).sum(axis=0)


def make_moe_fn(
    mesh,
    expert_fn,
    expert_axis="expert",
    batch_axis=None,
    capacity_factor=2.0,
    num_selected=1,
):
    """Global wrapper: ``(stacked_expert_params, x, gate_logits) -> y``.

    ``stacked_expert_params`` leaves are (E, ...) sharded over
    ``expert_axis``; ``x`` is (T, D) tokens (optionally sharded over
    ``batch_axis``), ``gate_logits`` (T, E) likewise. Capacity per
    expert = ceil(T_local * num_selected / E) * capacity_factor.
    """

    def _capacity(t_local, n_exp):
        return max(
            1,
            int(
                -(-(t_local * num_selected) // n_exp) * capacity_factor
            ),
        )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(expert_axis), P(batch_axis), P(batch_axis)),
        out_specs=P(batch_axis),
        check_rep=False,
    )
    def _moe(stacked_params, x, gate_logits):
        cap = _capacity(x.shape[0], int(mesh.shape[expert_axis]))
        return moe_apply(
            expert_fn,
            stacked_params,
            x,
            gate_logits,
            expert_axis,
            cap,
            num_selected=num_selected,
        )

    return _moe


def reference_moe(expert_fn, per_expert_params, x, gate_logits, num_selected=1):
    """Dense semantics the routed form must match (tests): every expert
    runs every token, outputs combined by the top-k gate."""
    idx, gate = topk_gate(gate_logits, num_selected)
    outs = jnp.stack(
        [expert_fn(p, x) for p in per_expert_params]
    )  # (E, T, D)
    t = jnp.arange(x.shape[0])
    picked = sum(
        outs[idx[:, j], t] * gate[:, j, None].astype(x.dtype)
        for j in range(num_selected)
    )
    return picked
