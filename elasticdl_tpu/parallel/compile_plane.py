"""Compile-plane fast path: executable reuse + speculative AOT compiles.

Elasticity in this framework means mesh re-formation: every
``ElasticPlane.establish()`` after a membership change used to retrace
and recompile the pjit train step from scratch, so the resize pause was
dominated by XLA compile time rather than by state movement — the
elastic-native cost ElasWave (arxiv 2510.00606) attacks with plan reuse
and the pjit scaling paper (arxiv 2204.06514) amortizes with
ahead-of-time lowering. This module is that amortization layer, shared
by the elastic trainer, the bench harness, and the tests:

- :class:`ExecutableCache` — jitted step callables (plus their AOT
  ``Compiled`` executables) keyed by (backend epoch, mesh signature,
  step-config signature). Re-establishing at a previously-seen world
  size hands back the SAME jit callable, so jax's own aval cache
  dispatches without retracing or recompiling. Entries are invalidated
  wholesale when the backend epoch advances (``leave_world`` drops every
  backend, so device handles inside old executables are dead).

- :class:`SpeculativeCompiler` — a cancellable daemon worker that AOT
  ``.lower().compile()``-s the train step for LIKELY NEXT world sizes
  (current ±1, membership-service hints) during steady-state training,
  inserting the results into the cache so a later establish at that size
  pays state re-placement only. Compiles run strictly outside the lock
  (edlint R5); the thread is daemonized AND joined on shutdown (R4); a
  hint for a size that never materializes is simply dropped.

- :func:`enable_persistent_cache` — wires jax's persistent compilation
  cache (``EDL_COMPILE_CACHE_DIR``) so a FRESH PROCESS (relaunched pod,
  promoted standby) skips the XLA compile too: the in-memory cache
  cannot outlive the process, but the HLO-keyed disk cache does.

Scope note: in-memory reuse pays off whenever the backend survives the
resize (single-process elastic planes, the CPU test/bench meshes built
over device subsets). A real multi-host re-form tears the backend down
(parallel/distributed.py), where the speculative compiles still warm the
persistent disk cache. docs/compile_plane.md has the full policy.
"""

import os
import threading
import time

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.utils import profiling


def _cpu_platform_selected():
    """Is this process pinned to the CPU backend? Answered from env and
    jax config ONLY — probing the backend itself (jax.default_backend)
    would initialize it, which the elastic worker must not do before
    its world forms."""
    import jax

    if os.environ.get("EDL_DIST_PLATFORM") == "cpu":
        return True
    selected = os.environ.get("JAX_PLATFORMS") or ""
    if not selected:
        try:
            selected = jax.config.jax_platforms or ""
        except AttributeError:
            selected = ""
    return selected.split(",")[0].strip().lower() == "cpu"


def enable_persistent_cache(cache_dir=None, probe_backend=False):
    """Point jax's persistent compilation cache at ``cache_dir`` (or
    ``$EDL_COMPILE_CACHE_DIR``). Idempotent; a no-op when neither is
    set. Survives ``clear_backends`` (it is jax config, not backend
    state), so one call at process start covers every re-formed world.

    CPU processes skip the cache unless ``EDL_COMPILE_CACHE_CPU=1``
    forces it: on this toolchain, EXECUTING a cache-reloaded executable
    with donated buffers on the CPU backend corrupts the native heap
    (measured: the local allreduce train resumed against a warm cache
    aborts in glibc inside the first train_step; the same drive with a
    cold cache, or without donation, is clean). The accelerator path is
    the production target and reloads cleanly.

    ``probe_backend=True`` additionally asks the live backend when the
    platform env/config is silent — catching an accelerator-less box
    jax lands on CPU implicitly. Callers that must not initialize a
    backend yet (the elastic worker before its world forms) keep the
    default False and are covered by the env answer
    (``EDL_DIST_PLATFORM=cpu`` is the documented CPU bring-up there).
    """
    cache_dir = cache_dir or os.environ.get("EDL_COMPILE_CACHE_DIR")
    if not cache_dir:
        return False
    import jax

    on_cpu = _cpu_platform_selected()
    if not on_cpu and probe_backend:
        try:
            on_cpu = jax.default_backend() == "cpu"
        except Exception:
            logger.debug(
                "backend probe for the compile cache failed; trusting "
                "the platform env",
                exc_info=True,
            )
    if on_cpu and not os.environ.get("EDL_COMPILE_CACHE_CPU"):
        logger.info(
            "persistent compile cache disabled on the CPU backend "
            "(cache-reloaded donated executables crash this toolchain; "
            "set EDL_COMPILE_CACHE_CPU=1 to force)"
        )
        return False

    try:
        if jax.config.jax_compilation_cache_dir == cache_dir:
            return True
    except AttributeError:
        logger.debug("jax build without a compilation-cache config")
        return False
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # jax's min-compile-time threshold (~1s) is kept DELIBERATELY: it
    # admits exactly the executables worth amortizing (the train steps)
    # while keeping the myriad tiny placement/broadcast programs out —
    # on this toolchain, reloading certain tiny cached CPU executables
    # crashes natively (measured: resume-from-checkpoint with a
    # zero-threshold warm cache segfaults in deserialization; with the
    # default threshold the same drive is clean, and the step compiles
    # still hit)
    logger.info("persistent compilation cache -> %s", cache_dir)
    return True


class CompileStats:
    """Per-owner compile-plane counters (a private
    :class:`profiling.Counters`), mirrored into the process-wide
    profiling registry so traces and bench lines see the same numbers
    without sharing the per-trainer tallies."""

    def __init__(self, prefix="compile_plane"):
        self._prefix = prefix
        self._local = profiling.Counters()

    def inc(self, name, value=1):
        self._local.inc(name, value)
        profiling.counters.inc("%s/%s" % (self._prefix, name), value)

    def add_time(self, name, seconds):
        self.inc(name + "_s", float(seconds))

    def get(self, name):
        return self._local.get(name)

    def snapshot(self):
        return self._local.snapshot()


def mesh_signature(mesh):
    """Hashable identity of a mesh placement: axis layout plus the flat
    device identity (id + process + platform). Two establishes at the
    same world size over the SAME live backend produce equal signatures;
    any difference in devices or layout misses the cache."""
    devices = tuple(
        (d.id, d.process_index, d.platform) for d in mesh.devices.flat
    )
    sizes = tuple(int(mesh.shape[name]) for name in mesh.axis_names)
    return (tuple(mesh.axis_names), sizes, devices)


def spec_signature(spec_tree):
    """Stable string form of a PartitionSpec pytree (or None): state
    specs are closed over by the step builder, so two step fns with
    different specs must never share a cache entry."""
    if spec_tree is None:
        return "None"
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(
        spec_tree, is_leaf=lambda x: x is None
    )
    return "%s|%s" % (treedef, [str(leaf) for leaf in leaves])


def args_signature(args):
    """(shape, dtype) tuple signature of flattened call args — the key
    an AOT-compiled executable is valid for."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(args)
    return tuple(
        (tuple(leaf.shape), np.dtype(leaf.dtype).str) for leaf in leaves
    )


class _Entry:
    __slots__ = (
        "step_fn",
        "aot",
        "dispatch_memo",
        "backend_epoch",
        "speculative",
    )

    def __init__(self, step_fn, backend_epoch, speculative=False):
        self.step_fn = step_fn
        self.aot = {}  # args_signature -> jax Compiled executable
        # batch-signature -> chosen callable (the hot loop must not
        # re-walk the whole TrainState signature every step)
        self.dispatch_memo = {}
        self.backend_epoch = backend_epoch
        self.speculative = speculative


class ExecutableCache:
    """LRU of compiled elastic train steps.

    Keys carry the backend epoch (parallel/distributed.py bumps it every
    time the backends are dropped): entries minted against a dead
    backend hold invalid device handles and are evicted on sight rather
    than reused. Lookups/inserts hold the lock only for dict bookkeeping
    — builders and compiles run strictly outside it (edlint R5).
    """

    def __init__(self, max_entries=8, stats=None):
        self._lock = threading.Lock()
        self._entries = {}
        self._order = []  # LRU, most recent last
        self._max = max(1, int(max_entries))
        self.stats = stats or CompileStats()

    def _current_epoch(self):
        from elasticdl_tpu.parallel import distributed

        return distributed.backend_epoch()

    def get(self, key, count=True):
        epoch = self._current_epoch()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.backend_epoch != epoch:
                # stale backend: the executable's devices are gone
                del self._entries[key]
                self._order.remove(key)
                entry = None
                self.stats.inc("stale_evictions")
            if entry is not None:
                self._order.remove(key)
                self._order.append(key)
        if count:
            self.stats.inc("hits" if entry is not None else "misses")
            if entry is not None and entry.speculative:
                entry.speculative = False  # first hit claims the win
                self.stats.inc("speculative_hits")
                # a background AOT compile just saved a resize pause —
                # worth a fleet-visible event (docs/observability.md)
                profiling.events.emit(
                    "speculative_compile_hit", key=str(key)
                )
        return entry

    def put(self, key, step_fn, speculative=False):
        entry = _Entry(step_fn, self._current_epoch(), speculative)
        with self._lock:
            if key in self._entries:
                self._order.remove(key)
            self._entries[key] = entry
            self._order.append(key)
            while len(self._order) > self._max:
                evicted = self._order.pop(0)
                del self._entries[evicted]
                self.stats.inc("lru_evictions")
        return entry

    def size(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._order[:] = []


def aot_compile(entry, abstract_args, stats=None):
    """AOT ``.lower().compile()`` of ``entry.step_fn`` for one argument
    signature; the Compiled executable lands on the entry so dispatch
    can skip tracing entirely. Returns the executable (or the existing
    one). ``abstract_args`` may mix concrete arrays and
    ShapeDtypeStructs — lowering never executes either."""
    sig = args_signature(abstract_args)
    compiled = entry.aot.get(sig)
    if compiled is not None:
        return compiled
    t0 = time.perf_counter()
    with profiling.annotate("compile_plane/aot_compile"):
        compiled = entry.step_fn.lower(*abstract_args).compile()
    entry.aot[sig] = compiled
    if stats is not None:
        stats.inc("aot_compiles")
        stats.add_time("aot_compile", time.perf_counter() - t0)
    return compiled


class SpeculativeCompiler:
    """Background AOT compiler for likely next world sizes.

    ``compile_fn(size)`` does the whole job for one hinted size (build
    mesh + step fn + AOT compile + cache insert) and is provided by the
    owner (the elastic trainer / the bench harness); it runs on a
    DAEMON thread, one size at a time, strictly outside this class's
    lock. ``hint(sizes)`` is non-blocking and deduplicates against both
    the pending queue and everything already attempted this generation.

    Lifecycle discipline (edlint R4, EDL_LOCKTRACE): the thread is
    daemonized AND ``shutdown()`` joins it; shutdown is cooperative — a
    size in flight finishes its (uninterruptible C++) compile and then
    observes the cancel event, while every still-pending size is
    DROPPED, never blocking the caller. The owner shuts the compiler
    down before tearing a world down and starts a fresh one after the
    next establish.
    """

    def __init__(self, compile_fn, stats=None, name="edl-spec-compile"):
        self._compile_fn = compile_fn
        self._name = name
        self.stats = stats or CompileStats()
        self._lock = threading.Lock()
        self._pending = []
        self._seen = set()
        self._cancel = threading.Event()
        self._wake = threading.Event()
        self._thread = None

    def hint(self, candidates):
        """Enqueue compile candidates (non-blocking, deduplicated).

        A candidate is either a bare world size (devices, int) or a
        ``(world_size, layout)`` tuple — the layout half is opaque
        hashable data the owner's ``compile_fn`` understands (the
        elastic trainer passes the solver's ``mesh_axes`` items, so a
        PLANNED layout change pre-compiles alongside planned size
        changes). Both forms dedup against everything already hinted
        this generation."""
        fresh = []
        with self._lock:
            if self._cancel.is_set():
                return
            for cand in candidates:
                if isinstance(cand, tuple):
                    key, size = tuple(cand), int(cand[0])
                else:
                    key = size = int(cand)
                if size > 0 and key not in self._seen:
                    self._seen.add(key)
                    self._pending.append(key)
                    fresh.append(key)
        if fresh:
            self.stats.inc("hinted", len(fresh))
            self._wake.set()

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True
        )
        self._thread.start()

    def _pop(self):
        with self._lock:
            if self._pending:
                return self._pending.pop(0)
            self._wake.clear()
            return None

    def _run(self):
        while not self._cancel.is_set():
            size = self._pop()
            if size is None:
                self._wake.wait(timeout=0.2)
                continue
            if self._cancel.is_set():
                break
            try:
                t0 = time.perf_counter()
                with profiling.annotate("compile_plane/speculative"):
                    built = self._compile_fn(size)
                if built:
                    self.stats.inc("speculative_builds")
                    self.stats.add_time(
                        "speculative_build", time.perf_counter() - t0
                    )
                else:
                    # size can never materialize on this backend (not
                    # enough devices / layout misfit): drop it
                    self.stats.inc("dropped")
            except Exception:
                self.stats.inc("failed")
                logger.warning(
                    "speculative compile for candidate %s failed",
                    size,
                    exc_info=True,
                )

    def pending_count(self):
        with self._lock:
            return len(self._pending)

    def idle(self):
        """True when nothing is pending or in flight (test/bench sync)."""
        with self._lock:
            busy = bool(self._pending) or self._wake.is_set()
        return not busy

    def shutdown(self, timeout=5.0):
        """Cancel pending work and join the worker.

        The thread is a daemon, so a compile wedged in C++ past the join
        timeout is abandoned safely (it can no longer insert: hint() and
        the run loop both observe the cancel event, and a stale-epoch
        insert is evicted by the cache anyway). Pending sizes are
        counted as dropped."""
        self._cancel.set()
        self._wake.set()
        with self._lock:
            dropped, self._pending = len(self._pending), []
        if dropped:
            self.stats.inc("dropped", dropped)
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
            if t.is_alive():
                logger.warning(
                    "speculative compiler still in a C++ compile at "
                    "shutdown; abandoned (daemon)"
                )
        self._thread = None
