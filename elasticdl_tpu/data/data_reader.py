"""Data reader contract + implementations + factory.

Parity: reference data/data_reader.py:17-196 — ``AbstractDataReader``
(read_records(task) / create_shards() / metadata), ``RecordIODataReader``
(per-file record indices), ``ODPSDataReader`` (table slices), and an
env-var-driven factory. The RecordIO backend here is the framework's own
EDLR format (see recordio.py); the ODPS backend is import-gated on the odps
SDK exactly like the reference is.
"""

import os
import threading
from abc import ABC, abstractmethod

from elasticdl_tpu.common.constants import ODPSConfig
from elasticdl_tpu.data.recordio import RecordIOReader, open_recordio


class Metadata:
    def __init__(self, column_names=None):
        self.column_names = column_names


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        pass

    @abstractmethod
    def read_records(self, task):
        """Yield raw records for ``task`` (records [task.start, task.end) of
        shard ``task.shard_name``)."""

    @abstractmethod
    def create_shards(self):
        """Return {shard_name: (start_index, num_records)}."""

    @property
    def records_output_types(self):
        """Element type hint for the dataset layer (bytes by default)."""
        return bytes

    @property
    def metadata(self):
        return Metadata()


class RecordIODataReader(AbstractDataReader):
    """Reads EDLR files from ``data_dir``; one shard per file.

    Record indices are file-local, so every shard starts at 0 — same
    convention as the reference (data_reader.py:79-87).
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        _check_required_kwargs(["data_dir"], kwargs)
        self._kwargs = kwargs
        self._readers = {}
        # read_records runs concurrently (task-prefetch warm pool +
        # consumer); an unsynchronized check-then-insert would build
        # duplicate readers and leak the loser's mmap/fd
        self._readers_lock = threading.Lock()
        self._closed = False

    def _reader(self, path):
        with self._readers_lock:
            if self._closed:
                raise RuntimeError("RecordIODataReader is closed")
            reader = self._readers.get(path)
        if reader is not None:
            return reader
        # cold open (C++ mmap reader when built; Python fallback) runs
        # OUTSIDE the lock so parallel warm reads of distinct shards
        # don't serialize on one another's mmap/open; a raced duplicate
        # loses the setdefault and closes itself — no fd leak
        reader = open_recordio(path)
        with self._readers_lock:
            # a cold open racing close() must not resurrect the reader
            # table: close() already drained it, so an insert here would
            # leave this mmap/fd open forever (nothing closes it again)
            winner = None if self._closed else (
                self._readers.setdefault(path, reader)
            )
        if winner is not reader:
            reader.close()
        if winner is None:
            raise RuntimeError("RecordIODataReader is closed")
        return winner

    def read_records(self, task):
        yield from self._reader(task.shard_name).read_range(
            task.start, task.end
        )

    def create_shards(self):
        data_dir = self._kwargs["data_dir"]
        shards = {}
        for f in sorted(os.listdir(data_dir)):
            p = os.path.join(data_dir, f)
            with RecordIOReader(p) as r:
                shards[p] = (0, len(r))
        return shards

    def close(self):
        with self._readers_lock:
            self._closed = True
            readers = list(self._readers.values())
            self._readers.clear()
        for r in readers:
            r.close()


class ODPSDataReader(AbstractDataReader):
    """Reads slices of an ODPS (MaxCompute) table.

    Shards are named ``{table}:shard_{i}`` and sized ``records_per_task``
    (reference data_reader.py:98-165). Requires the odps SDK at use time.
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = kwargs
        self._metadata = Metadata()
        # per-table reader cache: read_records used to construct a fresh
        # ODPSReader (table handshake and all) per TASK — the
        # RecordIODataReader._readers discipline, applied here. Locked:
        # concurrent warm reads must not race duplicate handshakes.
        self._readers = {}
        self._readers_lock = threading.Lock()

    def _get_reader(self, table_name):
        with self._readers_lock:
            if table_name in self._readers:
                return self._readers[table_name]
            _check_required_kwargs(
                ["project", "access_id", "access_key"], self._kwargs
            )
            from elasticdl_tpu.data.odps_io import ODPSReader

            reader = ODPSReader(
                project=self._kwargs["project"],
                access_id=self._kwargs["access_id"],
                access_key=self._kwargs["access_key"],
                table=table_name,
                endpoint=self._kwargs.get("endpoint"),
            )
            self._readers[table_name] = reader
            return reader

    @staticmethod
    def _table_of(shard_name):
        return shard_name.split(":")[0]

    def read_records(self, task):
        reader = self._get_reader(self._table_of(task.shard_name))
        with self._readers_lock:
            if self._metadata.column_names is None:
                columns = self._kwargs.get("columns")
                self._metadata.column_names = (
                    reader.table_schema_names()
                    if columns is None
                    else columns
                )
        yield from reader.read_batch(
            start=task.start,
            end=task.end,
            columns=self._metadata.column_names,
        )

    def create_shards(self):
        _check_required_kwargs(["table", "records_per_task"], self._kwargs)
        reader = self._get_reader(self._kwargs["table"])
        prefix = self._kwargs["table"] + ":shard_"
        table_size = reader.get_table_size()
        rpt = self._kwargs["records_per_task"]
        shards = {}
        start = 0
        for shard_id in range(table_size // rpt):
            shards[prefix + str(shard_id)] = (start, rpt)
            start += rpt
        left = table_size % rpt
        if left:
            shards[prefix + str(table_size // rpt)] = (start, left)
        return shards

    @property
    def metadata(self):
        return self._metadata

    def close(self):
        for reader in self._readers.values():
            close = getattr(reader, "close", None)
            if close is not None:
                close()
        self._readers.clear()


def create_data_reader(data_origin, records_per_task=None, **kwargs):
    """ODPS when its env credentials are set, else RecordIO over a dir.

    Mirrors reference data_reader.py:168-187.
    """
    if all(
        k in os.environ
        for k in (
            ODPSConfig.PROJECT_NAME,
            ODPSConfig.ACCESS_ID,
            ODPSConfig.ACCESS_KEY,
        )
    ):
        return ODPSDataReader(
            project=os.environ[ODPSConfig.PROJECT_NAME],
            access_id=os.environ[ODPSConfig.ACCESS_ID],
            access_key=os.environ[ODPSConfig.ACCESS_KEY],
            table=data_origin,
            endpoint=os.environ.get(ODPSConfig.ENDPOINT),
            records_per_task=records_per_task,
            **kwargs,
        )
    return RecordIODataReader(data_dir=data_origin)


def _check_required_kwargs(required_args, kwargs):
    missing = [k for k in required_args if k not in kwargs]
    if missing:
        raise ValueError(
            "The following required arguments are missing: %s"
            % ", ".join(missing)
        )
