"""TF-free structured record codec (tf.train.Example equivalent).

The reference's model-zoo ``dataset_fn`` parses ``tf.train.Example`` protos
with ``tf.io.parse_single_example`` + ``FixedLenFeature`` specs
(e.g. model_zoo/mnist_functional_api/mnist_functional_api.py:57-75). This
module provides the same contract without TensorFlow: an example is a dict
of named ndarrays serialized with the framework tensor codec, and
``parse_example`` validates/reshapes against ``FixedLenFeature`` specs.
"""

import numpy as np

from elasticdl_tpu.common.tensor import (
    Tensor,
    deserialize_tensors,
    serialize_tensors,
)


class FixedLenFeature:
    """Spec for a fixed-shape feature (tf.io.FixedLenFeature analog)."""

    def __init__(self, shape, dtype, default_value=None):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.default_value = default_value

    def __repr__(self):
        return "FixedLenFeature(%s, %s)" % (self.shape, self.dtype)


def encode_example(features):
    """Serialize {name: array-like} to bytes."""
    tensors = []
    for name in sorted(features):
        tensors.append(Tensor(name, np.asarray(features[name])))
    return serialize_tensors(tensors)


def decode_example(data):
    """Deserialize bytes back to {name: ndarray} without a spec."""
    return {t.name: t.values for t in deserialize_tensors(data)}


def parse_example(data, feature_spec):
    """Parse one serialized example against {name: FixedLenFeature}.

    Returns {name: ndarray} with each value cast + reshaped to its spec.
    Missing features fall back to ``default_value`` (or raise); extra
    features in the record are ignored — matching tf.io.parse_single_example
    behavior.
    """
    raw = decode_example(data)
    out = {}
    for name, spec in feature_spec.items():
        if name in raw:
            arr = np.asarray(raw[name])
            try:
                arr = arr.reshape(spec.shape)
            except ValueError:
                raise ValueError(
                    "feature %r has %d elements, spec shape %s"
                    % (name, arr.size, spec.shape)
                )
            out[name] = arr.astype(spec.dtype, copy=False)
        elif spec.default_value is not None:
            out[name] = np.full(
                spec.shape, spec.default_value, dtype=spec.dtype
            )
        else:
            raise KeyError("feature %r missing from example" % name)
    return out
