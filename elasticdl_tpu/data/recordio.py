"""Indexed record file format ("EDLR").

Role parity: the reference depends on the third-party RecordIO format
(pyrecordio; data/data_reader.py:60-95) whose key property is that a task can
address records by global index ``(file, start, end)`` with cheap seeks. This
is a fresh, self-describing format with the same property:

    file   := "EDLR" u32 version  record*  index  tail
    record := u32 payload_len, u32 crc32(payload), payload bytes
    index  := u64 count, u64 record_offset[count]
    tail   := u64 index_offset, "EDLX"

Writers append records and emit the offset index at close; readers mmap the
file, jump to the index via the fixed-size tail, and slice records in
[start, end) without scanning. A C++ reader with the same layout lives in
``elasticdl_tpu/native`` (used automatically when built; this module is the
portable fallback and the writer).
"""

import mmap
import os
import struct
import zlib

_MAGIC = b"EDLR"
_TAIL_MAGIC = b"EDLX"
_VERSION = 1
_HEADER = struct.Struct("<4sI")
_REC = struct.Struct("<II")
_TAIL = struct.Struct("<Q4s")


class RecordIOWriter:
    """Append-only writer; ``close()`` finalizes the index."""

    def __init__(self, path):
        self._f = open(path, "wb")
        self._f.write(_HEADER.pack(_MAGIC, _VERSION))
        self._offsets = []
        self._closed = False

    def write(self, payload):
        if self._closed:
            raise ValueError("writer is closed")
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise TypeError("record payload must be bytes")
        payload = bytes(payload)
        self._offsets.append(self._f.tell())
        self._f.write(_REC.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)

    @property
    def num_records(self):
        return len(self._offsets)

    def close(self):
        if self._closed:
            return
        index_offset = self._f.tell()
        self._f.write(struct.pack("<Q", len(self._offsets)))
        for off in self._offsets:
            self._f.write(struct.pack("<Q", off))
        self._f.write(_TAIL.pack(index_offset, _TAIL_MAGIC))
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            # error path: do NOT finalize — a tail-less file is rejected
            # by every reader as truncated, where a finalized partial
            # shard would silently serve incomplete data (same contract
            # as the native writer)
            self._f.close()
            self._closed = True
            return
        self.close()


class RecordIOReader:
    """Random-access reader over an EDLR file (mmap-backed)."""

    def __init__(self, path):
        self._path = path
        self._f = open(path, "rb")
        size = os.fstat(self._f.fileno()).st_size
        if size < _HEADER.size + _TAIL.size:
            raise ValueError("not an EDLR file (too small): %s" % path)
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, version = _HEADER.unpack_from(self._mm, 0)
        if magic != _MAGIC:
            raise ValueError("bad EDLR magic in %s" % path)
        if version != _VERSION:
            raise ValueError("unsupported EDLR version %d" % version)
        index_offset, tail_magic = _TAIL.unpack_from(
            self._mm, size - _TAIL.size
        )
        if tail_magic != _TAIL_MAGIC:
            raise ValueError("bad EDLR tail in %s (truncated write?)" % path)
        (count,) = struct.unpack_from("<Q", self._mm, index_offset)
        self._offsets = struct.unpack_from(
            "<%dQ" % count, self._mm, index_offset + 8
        )

    def __len__(self):
        return len(self._offsets)

    def read(self, i, validate=False):
        """Return payload bytes of record i."""
        off = self._offsets[i]
        length, crc = _REC.unpack_from(self._mm, off)
        start = off + _REC.size
        payload = self._mm[start : start + length]
        if validate and zlib.crc32(payload) != crc:
            raise ValueError(
                "crc mismatch at record %d of %s" % (i, self._path)
            )
        return payload

    def read_range(self, start, end):
        """Yield payloads of records [start, end) — the task read path."""
        end = min(end, len(self._offsets))
        for i in range(max(start, 0), end):
            yield self.read(i)

    def __iter__(self):
        return self.read_range(0, len(self))

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_recordio(path, payloads):
    """Convenience: write an iterable of bytes records; returns count."""
    with RecordIOWriter(path) as w:
        for p in payloads:
            w.write(p)
        return w.num_records


def create_recordio(path):
    """Writer factory: the C++ buffered writer when built, else Python.

    Same API (write/num_records/close, context manager) and identical
    bytes on disk; an exception inside the ``with`` block leaves a
    tail-less file both readers reject as truncated."""
    try:
        from elasticdl_tpu.native import NativeRecordIOWriter, native_lib

        if native_lib() is not None:
            return NativeRecordIOWriter(path)
    except (ImportError, OSError):
        pass  # native lib absent/unloadable: the Python writer is exact
    return RecordIOWriter(path)


def open_recordio(path):
    """Reader factory: the C++ mmap reader when built, else the Python one.

    Both expose the same API (len/read/read_range/close); build the native
    one with ``python -m elasticdl_tpu.native.build``.
    """
    try:
        from elasticdl_tpu.native import NativeRecordIOReader, native_lib

        if native_lib() is not None:
            return NativeRecordIOReader(path)
    except (ImportError, OSError):
        pass  # native lib absent/unloadable: the Python reader is exact
    return RecordIOReader(path)
