"""A tf.data-free streaming Dataset.

The reference hands user ``dataset_fn``s a ``tf.data.Dataset`` built from a
task-record generator (worker/task_data_service.py:126-188,
data/dataset_utils.py:4-24). This shim preserves the same fluent surface —
``map / filter / shuffle / batch / repeat / take / prefetch`` — over plain
Python iterators yielding numpy-structured elements, so model-zoo
``dataset_fn(dataset, mode, metadata)`` code ports contract-for-contract
without TensorFlow.

Elements are arbitrary pytrees (dicts/tuples) of np.ndarray-compatible
leaves; ``batch`` stacks leaf-wise. ``prefetch`` runs the upstream pipeline
in a daemon thread so host input overlaps TPU steps (the tf.data
``prefetch(1)`` role in reference worker.py:779).
"""

import collections
import queue
import random as _random
import threading

import numpy as np


def _tree_stack(elements):
    """Stack a list of same-structure elements leaf-wise."""
    first = elements[0]
    if isinstance(first, dict):
        return {
            k: _tree_stack([e[k] for e in elements]) for k in first
        }
    if isinstance(first, (tuple, list)):
        stacked = [
            _tree_stack([e[i] for e in elements]) for i in range(len(first))
        ]
        return tuple(stacked) if isinstance(first, tuple) else stacked
    return np.stack([np.asarray(e) for e in elements])


class Dataset:
    """Lazily-evaluated record stream; each transform returns a new Dataset."""

    def __init__(self, gen_factory):
        self._gen_factory = gen_factory

    @staticmethod
    def from_generator(gen_factory):
        """gen_factory: zero-arg callable returning a fresh iterator."""
        return Dataset(gen_factory)

    @staticmethod
    def from_tensors(elements):
        elements = list(elements)
        return Dataset(lambda: iter(elements))

    def map(self, fn):
        def gen():
            for x in self._gen_factory():
                yield fn(x)

        return Dataset(gen)

    def filter(self, pred):
        def gen():
            for x in self._gen_factory():
                if pred(x):
                    yield x

        return Dataset(gen)

    def shuffle(self, buffer_size, seed=None):
        """Streaming buffer shuffle with tf.data semantics."""

        def gen():
            rng = _random.Random(seed)
            buf = []
            for x in self._gen_factory():
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.randrange(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return Dataset(gen)

    def batch(self, batch_size, drop_remainder=False):
        def gen():
            batch = []
            for x in self._gen_factory():
                batch.append(x)
                if len(batch) == batch_size:
                    yield _tree_stack(batch)
                    batch = []
            if batch and not drop_remainder:
                yield _tree_stack(batch)

        return Dataset(gen)

    def repeat(self, count=None):
        def gen():
            n = 0
            while count is None or n < count:
                it = self._gen_factory()
                empty = True
                for x in it:
                    empty = False
                    yield x
                if empty:
                    return
                n += 1

        return Dataset(gen)

    def take(self, n):
        def gen():
            for i, x in enumerate(self._gen_factory()):
                if i >= n:
                    return
                yield x

        return Dataset(gen)

    def prefetch(self, buffer_size=1):
        """Run the upstream pipeline in a background thread.

        The producer is COOPERATIVELY CANCELLED when the consumer
        generator is closed/garbage-collected (an elastic spare park
        abandons its round mid-stream): without the cancel, a producer
        blocked on a full queue would leak forever, and one mid-
        ``get_task`` could keep pulling new work for a consumer that is
        gone."""

        def gen():
            q = queue.Queue(maxsize=max(1, buffer_size))
            _END = object()
            cancel = threading.Event()

            def put_or_cancel(item):
                """True once ``item`` is enqueued; False if cancelled
                first. EVERY producer put goes through here — including
                the terminal _END and exception sentinels: an unbounded
                q.put of those would block forever when the consumer was
                abandoned with a full queue right as the source
                exhausted (or raised), the exact leak the cooperative
                cancel exists to prevent."""
                while not cancel.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        return True
                    except queue.Full:
                        continue
                return False

            def produce():
                try:
                    for x in self._gen_factory():
                        if not put_or_cancel(x):
                            return
                    put_or_cancel(_END)
                except BaseException as e:  # propagate into consumer
                    put_or_cancel(e)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is _END:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                # runs on normal exhaustion, close(), and GC of an
                # abandoned consumer — the producer exits at its next
                # queue-put or cancellation check
                cancel.set()

        return Dataset(gen)

    def device_prefetch(self, buffer_size=2, placement=None):
        """Move elements to device ahead of consumption (double buffering).

        ``jax.device_put`` dispatches asynchronously, so keeping
        ``buffer_size`` batches in flight overlaps host->device transfer
        with the device compute consuming the previous batch — the role
        ``flax.jax_utils.prefetch_to_device`` plays in pmap pipelines.
        ``placement`` is an optional ``jax.sharding.Sharding`` (or
        device) for multi-chip batch layouts; default is the default
        device.

        Call it LAST in the pipeline (after ``batch``/``prefetch``):
        downstream host-side transforms on device arrays would bounce
        every element back. No TPU-memory risk at sane sizes: in-flight
        elements are bounded by ``buffer_size``.
        """

        def gen():
            import collections

            import jax

            def put(x):
                if placement is None:
                    return jax.device_put(x)
                return jax.device_put(x, placement)

            buf = collections.deque()
            for x in self._gen_factory():
                buf.append(put(x))
                if len(buf) > max(1, buffer_size):
                    yield buf.popleft()
            while buf:
                yield buf.popleft()

        return Dataset(gen)

    def __iter__(self):
        return iter(self._gen_factory())

    def as_numpy_iterator(self):
        return iter(self)


def create_dataset_from_tasks(tasks, data_reader):
    """Dataset over the records of a fixed task list.

    Parity: reference data/dataset_utils.py:4-24.
    """

    def gen():
        for task in tasks:
            yield from data_reader.read_records(task)

    return Dataset.from_generator(gen)
