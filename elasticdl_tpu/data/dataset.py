"""A tf.data-free streaming Dataset.

The reference hands user ``dataset_fn``s a ``tf.data.Dataset`` built from a
task-record generator (worker/task_data_service.py:126-188,
data/dataset_utils.py:4-24). This shim preserves the same fluent surface —
``map / filter / shuffle / batch / repeat / take / prefetch`` — over plain
Python iterators yielding numpy-structured elements, so model-zoo
``dataset_fn(dataset, mode, metadata)`` code ports contract-for-contract
without TensorFlow.

Elements are arbitrary pytrees (dicts/tuples) of np.ndarray-compatible
leaves; ``batch`` stacks leaf-wise. ``prefetch`` runs the upstream pipeline
in a daemon thread so host input overlaps TPU steps (the tf.data
``prefetch(1)`` role in reference worker.py:779).

Pipelined stages (docs/input_pipeline.md): ``map(fn, num_parallel_calls=N)``
decodes on a thread pool with a deterministic in-order merge and the same
cooperative-cancel discipline ``prefetch`` uses; ``batch`` assembles each
batch into preallocated per-leaf buffers filled in place (no per-element
``np.stack`` recursion). A Dataset can carry an
``input_stats.InputPlaneStats`` object; every transform propagates it and
charges its stage counter, so one object instruments a whole pipeline.
"""

import collections
import concurrent.futures
import queue
import random as _random
import threading
import time

import numpy as np


def _tree_stack(elements):
    """Stack a list of same-structure elements leaf-wise.

    Legacy per-element recursive assembly. Kept as the fallback for leaf
    types the preallocated fast path cannot host (bytes/str/object
    leaves, where a common dtype must be computed across the whole
    batch) and as the reference arm for equivalence tests/benches.
    """
    first = elements[0]
    if isinstance(first, dict):
        return {
            k: _tree_stack([e[k] for e in elements]) for k in first
        }
    if isinstance(first, (tuple, list)):
        stacked = [
            _tree_stack([e[i] for e in elements]) for i in range(len(first))
        ]
        return tuple(stacked) if isinstance(first, tuple) else stacked
    return np.stack([np.asarray(e) for e in elements])


class _NoFastPath(Exception):
    """A leaf the vectorized batch assembly must not host."""


def _batch_buffers(first, n):
    """Same-structure tree of preallocated (n, *leaf.shape) buffers."""
    if isinstance(first, dict):
        return {k: _batch_buffers(v, n) for k, v in first.items()}
    if isinstance(first, (tuple, list)):
        bufs = [_batch_buffers(v, n) for v in first]
        return tuple(bufs) if isinstance(first, tuple) else bufs
    leaf = np.asarray(first)
    if leaf.dtype == object or leaf.dtype.kind in "USV":
        # strings/bytes/object need a common dtype computed across the
        # whole batch — np.stack's job, not a fixed-width buffer's
        raise _NoFastPath
    return np.empty((n,) + leaf.shape, leaf.dtype)


def _batch_fill(buf, element, i):
    """Write ``element``'s leaves into row ``i`` of the buffers in place."""
    if isinstance(buf, dict):
        for k in buf:
            _batch_fill(buf[k], element[k], i)
    elif isinstance(buf, (tuple, list)):
        for b, e in zip(buf, element):
            _batch_fill(b, e, i)
    else:
        leaf = np.asarray(element)  # no copy when already an ndarray
        if leaf.dtype != buf.dtype or leaf.shape != buf.shape[1:]:
            # a leaf whose dtype/shape differs from element 0's: raw
            # assignment would silently cast (int buffer truncating a
            # float leaf) or broadcast where np.stack would promote or
            # raise — only the legacy path has the right semantics
            raise _NoFastPath
        buf[i] = leaf


def _tree_assemble(elements):
    """Vectorized batch assembly: one preallocated buffer per leaf,
    filled row by row — no per-element ``np.stack`` recursion and no
    intermediate per-leaf element lists. Falls back to ``_tree_stack``
    for leaf types the fixed-width buffers cannot host (bytes/str/
    object) and for batches whose leaf dtypes/shapes vary across
    elements (np.stack's promotion semantics apply there)."""
    try:
        buffers = _batch_buffers(elements[0], len(elements))
        for i, e in enumerate(elements):
            _batch_fill(buffers, e, i)
    except _NoFastPath:
        return _tree_stack(elements)
    return buffers


class Dataset:
    """Lazily-evaluated record stream; each transform returns a new Dataset."""

    def __init__(self, gen_factory, stats=None):
        self._gen_factory = gen_factory
        # optional InputPlaneStats; inherited by every derived Dataset so
        # one object instruments the whole pipeline (map charges parse_s,
        # batch charges batch_s, prefetch charges consumer_starved_s)
        self._stats = stats

    @staticmethod
    def from_generator(gen_factory, stats=None):
        """gen_factory: zero-arg callable returning a fresh iterator."""
        return Dataset(gen_factory, stats=stats)

    @staticmethod
    def from_tensors(elements):
        elements = list(elements)
        return Dataset(lambda: iter(elements))

    def map(self, fn, num_parallel_calls=None):
        """Apply ``fn`` per element; with ``num_parallel_calls`` > 1 run it
        on a thread pool with a DETERMINISTIC IN-ORDER merge.

        Parallel semantics match the serial path exactly: elements come
        out in input order, and an exception raised by ``fn`` on element
        i surfaces to the consumer after element i-1, however the pool
        interleaved the calls. The pool is cooperatively cancelled when
        the consumer generator is closed/abandoned (same discipline as
        ``prefetch``): no new elements are pulled from the source and
        unconsumed futures are cancelled.
        """
        stats = self._stats
        # parse timing accumulates in generator locals and hits the
        # (locked) stats object once at the end, not per record — the
        # same discipline task_data_service._yield_records uses; with a
        # decode pool, per-record stats.add would make N threads
        # contend on one lock at exactly the stage being parallelized.
        if not num_parallel_calls or num_parallel_calls <= 1:

            def gen():
                if stats is None:
                    for x in self._gen_factory():
                        yield fn(x)
                    return
                parse_s = 0.0
                perf = time.perf_counter
                try:
                    for x in self._gen_factory():
                        t0 = perf()
                        out = fn(x)
                        parse_s += perf() - t0
                        yield out
                finally:
                    stats.add("parse_s", parse_s)

            return Dataset(gen, stats=stats)

        window = 2 * num_parallel_calls

        if stats is None:
            apply = fn
        else:

            def apply(x):
                # duration rides back with the result; the merge loop
                # accumulates it lock-free
                t0 = time.perf_counter()
                out = fn(x)
                return time.perf_counter() - t0, out

        def gen():
            pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_parallel_calls,
                thread_name_prefix="edl-map",
            )
            pending = collections.deque()
            parse_s = 0.0

            def resolve(future):
                # .result() re-raises fn's exception at the failing
                # element's ordinal position
                if stats is None:
                    return future.result()
                nonlocal parse_s
                dt, out = future.result()
                parse_s += dt
                return out

            try:
                for x in self._gen_factory():
                    pending.append(pool.submit(apply, x))
                    if len(pending) >= window:
                        yield resolve(pending.popleft())
                while pending:
                    yield resolve(pending.popleft())
            finally:
                # normal exhaustion, an fn error, or an abandoned
                # consumer: stop pulling from the source (the loop above
                # is consumer-driven, so exiting it IS the stop), drop
                # not-yet-started work, don't block on in-flight calls
                pool.shutdown(wait=False, cancel_futures=True)
                if stats is not None:
                    stats.add("parse_s", parse_s)

        return Dataset(gen, stats=stats)

    def filter(self, pred):
        def gen():
            for x in self._gen_factory():
                if pred(x):
                    yield x

        return Dataset(gen, stats=self._stats)

    def shuffle(self, buffer_size, seed=None, reshuffle_each_iteration=True):
        """Streaming buffer shuffle with tf.data semantics.

        Like tf.data, each iteration reshuffles by default: a seeded
        dataset is deterministic WITHIN one iteration, but a ``repeat``
        re-iteration draws a different order (epoch 2 must not replay
        epoch 1's order). ``reshuffle_each_iteration=False`` restores
        the identical-replay behavior.
        """
        iteration = collections.deque((0,))  # mutable epoch counter

        def gen():
            epoch = iteration[0]
            iteration[0] = epoch + 1
            if seed is None:
                rng = _random.Random()
            elif reshuffle_each_iteration:
                # distinct deterministic stream per iteration
                rng = _random.Random(seed * 0x9E3779B1 + epoch)
            else:
                rng = _random.Random(seed)
            buf = []
            for x in self._gen_factory():
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.randrange(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return Dataset(gen, stats=self._stats)

    def batch(self, batch_size, drop_remainder=False, vectorized=True):
        """Group ``batch_size`` elements into one stacked pytree.

        ``vectorized`` (default) assembles each batch into preallocated
        per-leaf buffers filled in place — one pass, no per-element
        ``np.stack`` recursion; False keeps the legacy ``_tree_stack``
        path (the equivalence/bench reference arm). Both produce
        identical arrays for numeric pytrees; bytes/str/object leaves
        take the legacy path either way.
        """
        assemble = _tree_assemble if vectorized else _tree_stack
        stats = self._stats

        if stats is None:
            emit = assemble
        else:

            def emit(batch):
                t0 = time.perf_counter()
                out = assemble(batch)
                stats.add("batch_s", time.perf_counter() - t0)
                stats.count("batches")
                return out

        def gen():
            batch = []
            for x in self._gen_factory():
                batch.append(x)
                if len(batch) == batch_size:
                    yield emit(batch)
                    batch = []
            if batch and not drop_remainder:
                yield emit(batch)

        return Dataset(gen, stats=stats)

    def repeat(self, count=None):
        def gen():
            n = 0
            while count is None or n < count:
                it = self._gen_factory()
                empty = True
                for x in it:
                    empty = False
                    yield x
                if empty:
                    return
                n += 1

        return Dataset(gen, stats=self._stats)

    def take(self, n):
        def gen():
            for i, x in enumerate(self._gen_factory()):
                if i >= n:
                    return
                yield x

        return Dataset(gen, stats=self._stats)

    def prefetch(self, buffer_size=1):
        """Run the upstream pipeline in a background thread.

        The producer is COOPERATIVELY CANCELLED when the consumer
        generator is closed/garbage-collected (an elastic spare park
        abandons its round mid-stream): without the cancel, a producer
        blocked on a full queue would leak forever, and one mid-
        ``get_task`` could keep pulling new work for a consumer that is
        gone."""

        def gen():
            q = queue.Queue(maxsize=max(1, buffer_size))
            _END = object()
            cancel = threading.Event()

            def put_or_cancel(item):
                """True once ``item`` is enqueued; False if cancelled
                first. EVERY producer put goes through here — including
                the terminal _END and exception sentinels: an unbounded
                q.put of those would block forever when the consumer was
                abandoned with a full queue right as the source
                exhausted (or raised), the exact leak the cooperative
                cancel exists to prevent."""
                while not cancel.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        return True
                    except queue.Full:
                        continue
                return False

            def produce():
                try:
                    for x in self._gen_factory():
                        if not put_or_cancel(x):
                            return
                    put_or_cancel(_END)
                except BaseException as e:  # propagate into consumer
                    put_or_cancel(e)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            stats = self._stats
            try:
                while True:
                    if stats is None:
                        item = q.get()
                    else:
                        # a consumer blocked here is STARVED: the device
                        # outran the host input pipeline
                        t0 = time.perf_counter()
                        item = q.get()
                        stats.add(
                            "consumer_starved_s",
                            time.perf_counter() - t0,
                        )
                    if item is _END:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                # runs on normal exhaustion, close(), and GC of an
                # abandoned consumer — the producer exits at its next
                # queue-put or cancellation check
                cancel.set()

        return Dataset(gen, stats=self._stats)

    def device_prefetch(self, buffer_size=2, placement=None):
        """Move elements to device ahead of consumption (double buffering).

        ``jax.device_put`` dispatches asynchronously, so keeping
        ``buffer_size`` batches in flight overlaps host->device transfer
        with the device compute consuming the previous batch — the role
        ``flax.jax_utils.prefetch_to_device`` plays in pmap pipelines.
        ``placement`` is an optional ``jax.sharding.Sharding`` (or
        device) for multi-chip batch layouts; default is the default
        device.

        Call it LAST in the pipeline (after ``batch``/``prefetch``):
        downstream host-side transforms on device arrays would bounce
        every element back. No TPU-memory risk at sane sizes: in-flight
        elements are bounded by ``buffer_size``.
        """

        def gen():
            import collections

            import jax

            def put(x):
                if placement is None:
                    return jax.device_put(x)
                return jax.device_put(x, placement)

            buf = collections.deque()
            for x in self._gen_factory():
                buf.append(put(x))
                if len(buf) > max(1, buffer_size):
                    yield buf.popleft()
            while buf:
                yield buf.popleft()

        return Dataset(gen, stats=self._stats)

    def __iter__(self):
        return iter(self._gen_factory())

    def as_numpy_iterator(self):
        return iter(self)


def create_dataset_from_tasks(tasks, data_reader):
    """Dataset over the records of a fixed task list.

    Parity: reference data/dataset_utils.py:4-24.
    """

    def gen():
        for task in tasks:
            yield from data_reader.read_records(task)

    return Dataset.from_generator(gen)
