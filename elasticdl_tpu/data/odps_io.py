"""ODPS (MaxCompute) table IO.

Parity: reference data/odps_io.py — a retrying slice reader and a writer
over the Alibaba ODPS SDK. The SDK is optional; importing this module is
cheap and classes raise a clear error at construction when the SDK is
absent (the reference hard-imports it; gating keeps the framework usable
without the dependency).
"""

import time

from elasticdl_tpu.common.log_utils import default_logger as logger

_MAX_RETRIES = 3
_RETRY_DELAY_SECS = 5


def _require_odps():
    try:
        import odps  # noqa: F401

        return odps
    except ImportError as e:
        raise ImportError(
            "ODPS support requires the `odps` (pyodps) SDK, which is not "
            "installed in this environment"
        ) from e


class ODPSReader:
    """Reads [start, end) row slices of one table, with retry.

    Mirrors reference odps_io.py:92-237 behavior (slice read + retrying
    read_batch); the parallel cache-batch heuristic is replaced by the
    framework's Dataset.prefetch thread.
    """

    def __init__(self, project, access_id, access_key, table, endpoint=None):
        odps = _require_odps()
        self._odps = odps.ODPS(
            access_id=access_id,
            secret_access_key=access_key,
            project=project,
            endpoint=endpoint,
        )
        self._table = self._odps.get_table(table)

    def get_table_size(self):
        with self._table.open_reader() as reader:
            return reader.count

    def table_schema_names(self):
        return [c.name for c in self._table.table_schema.columns]

    def read_batch(self, start, end, columns=None):
        """Yield rows (as tuples of column values) for [start, end)."""
        for attempt in range(_MAX_RETRIES):
            try:
                with self._table.open_reader() as reader:
                    for record in reader.read(
                        start=start, count=end - start, columns=columns
                    ):
                        yield tuple(record.values)
                return
            except Exception as e:
                if attempt == _MAX_RETRIES - 1:
                    raise
                logger.warning(
                    "ODPS read_batch failed (%s); retrying in %ds",
                    e,
                    _RETRY_DELAY_SECS,
                )
                time.sleep(_RETRY_DELAY_SECS)


class ODPSWriter:
    """Writes rows to a table, creating it from a schema if needed.

    Mirrors reference odps_io.py:273-344.
    """

    def __init__(
        self,
        project,
        access_id,
        access_key,
        table,
        endpoint=None,
        columns=None,
        column_types=None,
    ):
        odps = _require_odps()
        self._odps_mod = odps
        self._odps = odps.ODPS(
            access_id=access_id,
            secret_access_key=access_key,
            project=project,
            endpoint=endpoint,
        )
        self._table_name = table
        self._columns = columns
        self._column_types = column_types

    def _ensure_table(self):
        if self._odps.exist_table(self._table_name):
            return
        if not self._columns or not self._column_types:
            raise ValueError(
                "columns and column_types are required to create table %s"
                % self._table_name
            )
        schema = ",".join(
            "%s %s" % (c, t)
            for c, t in zip(self._columns, self._column_types)
        )
        self._odps.create_table(
            self._table_name, schema, if_not_exists=True
        )

    def from_iterator(self, records_iter):
        self._ensure_table()
        table = self._odps.get_table(self._table_name)
        with table.open_writer() as writer:
            for row in records_iter:
                writer.write(list(row))
