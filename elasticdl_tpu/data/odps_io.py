"""ODPS (MaxCompute) table IO.

Parity: reference data/odps_io.py — a *parallel* retrying slice reader
(pipelined large-slice downloads over a worker pool, sized by the
cache-batch heuristic: sample rows, estimate bytes/batch, bound each
download at ~20 MB / 50 batches — odps_io.py:92-270) and a writer. The
SDK is optional; importing this module is cheap and classes raise a clear
error at construction when the SDK is absent (the reference hard-imports
it; gating keeps the framework usable without the dependency).
"""

import random
import time
from concurrent.futures import ThreadPoolExecutor
from queue import Queue

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger

_MAX_RETRIES = 3
_RETRY_DELAY_SECS = 5
_SAMPLE_ROWS = 10
_MAX_CACHE_BATCHES = 50
_DOWNLOAD_BYTES_BOUND = 20 * 1000000
_STREAM_CHUNK_ROWS = 4096


def _nested_size(rows):
    """Rough byte size of a list of row tuples (heuristic input)."""
    total = 0
    for row in rows:
        for value in row:
            if isinstance(value, (bytes, str)):
                total += len(value)
            else:
                total += np.asarray(value).nbytes
    return max(1, total)


def _require_odps():
    try:
        import odps  # noqa: F401

        return odps
    except ImportError as e:
        raise ImportError(
            "ODPS support requires the `odps` (pyodps) SDK, which is not "
            "installed in this environment"
        ) from e


class ODPSReader:
    """Parallel retrying reader over [start, end) row slices.

    Role parity with reference odps_io.py:92-270: small training batches
    must not each pay an HTTP round trip, so reads happen as pipelined
    *large* slices (``batch_size x cache_batch_count`` rows, sized by
    :meth:`_estimate_cache_batch_count`) fetched by a thread pool while
    earlier slices are consumed.
    """

    def __init__(
        self,
        project,
        access_id,
        access_key,
        table,
        endpoint=None,
        num_processes=None,
        partition=None,
    ):
        odps = _require_odps()
        self._odps = odps.ODPS(
            access_id=access_id,
            secret_access_key=access_key,
            project=project,
            endpoint=endpoint,
        )
        self._table = self._odps.get_table(table)
        self._partition = partition
        self._num_processes = num_processes

    def get_table_size(self):
        with self._table.open_reader(partition=self._partition) as reader:
            return reader.count

    def table_schema_names(self):
        return [c.name for c in self._table.table_schema.columns]

    def _read_slice(self, start, end, columns=None):
        """All rows of [start, end) as a list, with retry."""
        for attempt in range(_MAX_RETRIES):
            try:
                with self._table.open_reader(
                    partition=self._partition
                ) as reader:
                    return [
                        tuple(record.values)
                        for record in reader.read(
                            start=start,
                            count=end - start,
                            columns=columns,
                        )
                    ]
            except Exception as e:
                if attempt == _MAX_RETRIES - 1:
                    raise
                logger.warning(
                    "ODPS read failed (%s); retrying in %ds",
                    e,
                    _RETRY_DELAY_SECS,
                )
                time.sleep(_RETRY_DELAY_SECS)

    def read_batch(self, start, end, columns=None):
        """Yield rows (as tuples of column values) for [start, end).

        Streams in bounded chunks: memory stays O(chunk) for tasks
        spanning many rows, and a retry repeats only the failed chunk
        instead of re-yielding rows already consumed.
        """
        for chunk_start in range(start, end, _STREAM_CHUNK_ROWS):
            chunk_end = min(chunk_start + _STREAM_CHUNK_ROWS, end)
            for row in self._read_slice(chunk_start, chunk_end, columns):
                yield row

    def _estimate_cache_batch_count(self, columns, table_size, batch_size):
        """Batches per download so each HTTP fetch moves ~20 MB
        (reference odps_io.py:243-270): sample a few rows, scale."""
        if table_size < _SAMPLE_ROWS:
            return 1
        sample = self._read_slice(0, _SAMPLE_ROWS, columns)
        bytes_per_batch = (
            _nested_size(sample) * batch_size / _SAMPLE_ROWS
        )
        estimate = max(int(_DOWNLOAD_BYTES_BOUND / bytes_per_batch), 1)
        return min(estimate, _MAX_CACHE_BATCHES)

    def to_iterator(
        self,
        num_workers,
        worker_index,
        batch_size,
        epochs=1,
        shuffle=False,
        columns=None,
        cache_batch_count=None,
        limit=-1,
    ):
        """Yield lists of up to ``batch_size`` rows for this worker's
        share of the table, downloading large slices concurrently."""
        if worker_index >= num_workers:
            raise ValueError(
                "index of worker should be less than number of workers"
            )
        if batch_size <= 0:
            raise ValueError("batch_size should be positive")

        table_size = self.get_table_size()
        if 0 < limit < table_size:
            table_size = limit
        if columns is None:
            columns = self.table_schema_names()
        if cache_batch_count is None:
            cache_batch_count = self._estimate_cache_batch_count(
                columns, table_size, batch_size
            )
        # disjoint (start, end) slices: the stride shrinks when there are
        # fewer natural slices than workers, and ends always match the
        # stride so no two workers read overlapping rows
        stride = batch_size * cache_batch_count
        if len(range(0, table_size, stride)) < num_workers:
            stride = max(1, table_size // num_workers)
        slices = [
            (s, min(s + stride, table_size))
            for s in range(0, table_size, stride)
        ]
        my_slices = [
            s
            for i, s in enumerate(slices)
            if i % num_workers == worker_index
        ]
        if not my_slices:
            return
        plan = []
        for _ in range(epochs):
            epoch_slices = list(my_slices)
            if shuffle:
                random.shuffle(epoch_slices)  # fresh order every epoch
            plan.extend(epoch_slices)

        pool_size = min(8, len(plan))
        if self._num_processes:
            pool_size = min(self._num_processes, pool_size)

        executor = ThreadPoolExecutor(max_workers=pool_size)
        in_flight = Queue()
        try:
            def submit(i):
                start, end = plan[i]
                in_flight.put(
                    executor.submit(self._read_slice, start, end, columns)
                )

            # prime the pipeline, then keep one new download in flight
            # per slice consumed
            for i in range(pool_size):
                submit(i)
            next_i = pool_size
            while not in_flight.empty():
                if next_i < len(plan):
                    submit(next_i)
                    next_i += 1
                # single-threaded producer==consumer: every submit()
                # precedes this pop and the loop is guarded by
                # in_flight.empty(), so the queue can never be empty
                # here — get_nowait keeps that invariant checkable
                # (edlint R3) instead of hiding a hang behind a
                # blocking get
                rows = in_flight.get_nowait().result()
                for j in range(0, len(rows), batch_size):
                    yield rows[j : j + batch_size]
        finally:
            # an abandoned iterator must not block on in-flight
            # downloads (and their retry sleeps)
            executor.shutdown(wait=False, cancel_futures=True)


class ODPSWriter:
    """Writes rows to a table, creating it from a schema if needed.

    Mirrors reference odps_io.py:273-344.
    """

    def __init__(
        self,
        project,
        access_id,
        access_key,
        table,
        endpoint=None,
        columns=None,
        column_types=None,
    ):
        odps = _require_odps()
        self._odps_mod = odps
        self._odps = odps.ODPS(
            access_id=access_id,
            secret_access_key=access_key,
            project=project,
            endpoint=endpoint,
        )
        self._table_name = table
        self._columns = columns
        self._column_types = column_types

    def _ensure_table(self):
        if self._odps.exist_table(self._table_name):
            return
        if not self._columns or not self._column_types:
            raise ValueError(
                "columns and column_types are required to create table %s"
                % self._table_name
            )
        schema = ",".join(
            "%s %s" % (c, t)
            for c, t in zip(self._columns, self._column_types)
        )
        self._odps.create_table(
            self._table_name, schema, if_not_exists=True
        )

    def from_iterator(self, records_iter):
        self._ensure_table()
        table = self._odps.get_table(self._table_name)
        with table.open_writer() as writer:
            for row in records_iter:
                writer.write(list(row))
