"""Convert (image, label) datasets into sharded EDLR files.

Parity: reference data/recordio_gen/image_label.py — partition a dataset
into N records per shard file under ``{dir}/data-%05d`` so the master can
shard-address them. Works on in-memory arrays or any (image, label)
iterable; e.g. mnist/cifar10 arrays from any source.

Usage:
    python -m elasticdl_tpu.data.recordio_gen.image_label \
        --output_dir /data/mnist --records_per_shard 4096 --dataset mnist
"""

import argparse
import os

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import create_recordio


def convert(iterable, output_dir, records_per_shard=4096, partition=""):
    """Write examples; returns the list of shard files created."""
    os.makedirs(output_dir, exist_ok=True)
    files = []
    writer = None
    count = 0
    try:
        for image, label in iterable:
            if writer is None or count % records_per_shard == 0:
                if writer is not None:
                    writer.close()
                name = "data%s-%05d" % (
                    "-" + partition if partition else "",
                    len(files),
                )
                path = os.path.join(output_dir, name)
                files.append(path)
                writer = create_recordio(path)
            writer.write(
                encode_example(
                    {
                        "image": np.asarray(image),
                        "label": np.asarray(label, dtype=np.int64).reshape(
                            -1
                        ),
                    }
                )
            )
            count += 1
    finally:
        if writer is not None:
            writer.close()
    return files


def _load_builtin(name):
    """Synthesize or load well-known datasets without TF."""
    if name == "synthetic-mnist":
        rng = np.random.default_rng(0)
        n = 4096
        images = rng.integers(0, 256, size=(n, 28, 28)).astype(np.float32)
        labels = rng.integers(0, 10, size=(n,))
        return zip(images, labels)
    raise ValueError(
        "unknown dataset %r (pass your own arrays via convert())" % name
    )


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--records_per_shard", type=int, default=4096)
    parser.add_argument("--dataset", default="synthetic-mnist")
    parser.add_argument("--partition", default="")
    args = parser.parse_args(argv)
    files = convert(
        _load_builtin(args.dataset),
        args.output_dir,
        args.records_per_shard,
        args.partition,
    )
    print("\n".join(files))


if __name__ == "__main__":
    main()
