"""Convert frappe-style CTR data (libffm text) into EDLR shards.

Parity: reference data/recordio_gen/frappe_recordio_gen.py — each input
line is ``label feat:field:... feat:...``; features become an int64 id
vector and the label a single int64, matching the deepfm zoo dataset_fn.
"""

import argparse
import os

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import create_recordio


def parse_line(line, num_features=10):
    parts = line.strip().split()
    if not parts:
        return None
    label = int(float(parts[0]))
    feats = []
    for tok in parts[1 : num_features + 1]:
        feats.append(int(tok.split(":")[0]))
    while len(feats) < num_features:
        feats.append(0)
    return np.asarray(feats, dtype=np.int64), np.asarray(
        [label], dtype=np.int64
    )


def convert(input_file, output_dir, records_per_shard=8192, num_features=10):
    os.makedirs(output_dir, exist_ok=True)
    files = []
    writer = None
    count = 0
    with open(input_file) as f:
        for line in f:
            parsed = parse_line(line, num_features)
            if parsed is None:
                continue
            if writer is None or count % records_per_shard == 0:
                if writer is not None:
                    writer.close()
                path = os.path.join(
                    output_dir, "frappe-%05d" % len(files)
                )
                files.append(path)
                writer = create_recordio(path)
            feature, label = parsed
            writer.write(
                encode_example({"feature": feature, "label": label})
            )
            count += 1
    if writer is not None:
        writer.close()
    return files


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--input", required=True)
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--records_per_shard", type=int, default=8192)
    parser.add_argument("--num_features", type=int, default=10)
    args = parser.parse_args(argv)
    files = convert(
        args.input,
        args.output_dir,
        args.records_per_shard,
        args.num_features,
    )
    print("\n".join(files))


if __name__ == "__main__":
    main()
