"""PySpark job: distributed dataset -> EDLR shards.

Parity: reference data/recordio_gen/sample_pyspark_recordio_gen/
spark_gen_recordio.py — each Spark partition writes its own shard files
via ``mapPartitionsWithIndex``; the pyspark dependency is required only
when actually submitting the job.
"""

import argparse


def write_partition(index, records, output_dir, records_per_shard, prepare):
    """Runs on executors: converts one partition's records."""
    from elasticdl_tpu.data.recordio_gen.image_label import convert

    examples = (prepare(r) for r in records)
    files = convert(
        examples,
        output_dir,
        records_per_shard=records_per_shard,
        partition="p%05d" % index,
    )
    return files


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--training_data_dir", required=True)
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--records_per_shard", type=int, default=4096)
    parser.add_argument("--num_workers", type=int, default=2)
    args = parser.parse_args(argv)

    from pyspark import SparkContext  # noqa: PLC0415 — executor-only dep

    sc = SparkContext()
    rdd = sc.binaryFiles(args.training_data_dir).repartition(
        args.num_workers
    )

    def prepare(pair):
        # filename encodes the label as its parent directory, matching
        # the reference mnist ingestion convention
        import numpy as np

        path, payload = pair
        label = int(path.split("/")[-2])
        image = np.frombuffer(payload, dtype=np.uint8)
        return image, label

    rdd.mapPartitionsWithIndex(
        lambda idx, it: write_partition(
            idx, it, args.output_dir, args.records_per_shard, prepare
        )
    ).collect()
    sc.stop()


if __name__ == "__main__":
    main()
