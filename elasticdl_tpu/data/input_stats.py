"""Per-stage counters for the worker input plane.

One ``InputPlaneStats`` object rides a whole dataset round: the task
data service charges task-starvation/read time, ``Dataset.map`` charges
parse time, ``Dataset.batch`` charges batch-assembly time, and
``Dataset.prefetch`` charges the time its consumer spent waiting on an
empty buffer. The worker logs a snapshot at every task-stream boundary
(docs/input_pipeline.md has the counter glossary), and ``bench.py
--input`` reports the same counters for its serial vs pipelined arms.

Time counters are wall seconds as seen by the charging stage; with
parallel decode the parse counter aggregates across pool threads, so it
can legitimately exceed the round's wall time (it is CPU-seconds of
decode, not a latency).
"""

import threading
import time


class InputPlaneStats:
    """Thread-safe additive counters for the input pipeline stages."""

    TIME_FIELDS = (
        # consumer waited for the master to hand over a task
        "task_starved_s",
        # pulling records out of the data reader
        "read_s",
        # user parse fn (Dataset.map); CPU-seconds across decode threads
        "parse_s",
        # batch assembly (Dataset.batch)
        "batch_s",
        # downstream consumer waited on an empty prefetch buffer
        "consumer_starved_s",
        # task acknowledgment RPCs (sync acks charge the hot loop,
        # queued acks charge their boundary drain)
        "ack_s",
    )
    COUNT_FIELDS = ("tasks", "records", "batches")

    def __init__(self):
        self._lock = threading.Lock()
        self._values = {}
        self.reset()

    def reset(self):
        with self._lock:
            for f in self.TIME_FIELDS + self.COUNT_FIELDS:
                self._values[f] = 0.0 if f in self.TIME_FIELDS else 0

    def add(self, field, seconds):
        with self._lock:
            self._values[field] += seconds

    def count(self, field, n=1):
        with self._lock:
            self._values[field] += n

    def timed(self, field):
        """Context manager charging its body's wall time to ``field``."""
        return _Timed(self, field)

    def snapshot(self):
        with self._lock:
            return dict(self._values)

    def publish_to(self, registry, worker=""):
        """Mirror the current counters into ``registry`` gauges
        (``edl_input_stage_seconds{stage=...}`` / ``edl_input_count``)
        so a stalled stream is visible mid-epoch — the worker's own
        boundary log only fires at stream ends. Called at the telemetry
        snapshot cadence, never per record."""
        snap = self.snapshot()
        # gauges, not counters (the stats reset at stream boundaries),
        # so no Prometheus-reserved _total suffix
        seconds = registry.gauge(
            "edl_input_stage_seconds",
            "Input-plane stage seconds since the last stream boundary",
            labels=("worker", "stage"),
        )
        counts = registry.gauge(
            "edl_input_count",
            "Input-plane item counts since the last stream boundary",
            labels=("worker", "kind"),
        )
        worker = str(worker)
        for f in self.TIME_FIELDS:
            seconds.set(snap[f], worker=worker, stage=f[: -len("_s")])
        for f in self.COUNT_FIELDS:
            counts.set(snap[f], worker=worker, kind=f)
        return snap

    def format_line(self):
        """One log line: counts plus per-stage times in ms."""
        s = self.snapshot()
        times = " ".join(
            "%s=%.0fms" % (f[: -len("_s")], s[f] * 1e3)
            for f in self.TIME_FIELDS
        )
        return "input-plane: tasks=%d records=%d batches=%d %s" % (
            s["tasks"],
            s["records"],
            s["batches"],
            times,
        )


class _Timed:
    def __init__(self, stats, field):
        self._stats = stats
        self._field = field

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.add(self._field, time.perf_counter() - self._t0)
        return False
