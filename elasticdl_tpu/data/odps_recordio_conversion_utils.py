"""ODPS table rows -> structured examples -> EDLR shard files.

Parity: reference data/odps_recordio_conversion_utils.py — convert
MaxCompute rows (sequences of column values) into the framework's example
records partitioned into shard files. Numeric columns become float32/int64
features named by column; string columns are utf-8 byte features.
"""

import os

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import create_recordio


def row_to_example(row, column_names):
    features = {}
    for name, value in zip(column_names, row):
        if isinstance(value, (int, np.integer)):
            features[name] = np.asarray([value], dtype=np.int64)
        elif isinstance(value, (float, np.floating)):
            features[name] = np.asarray([value], dtype=np.float32)
        elif isinstance(value, bytes):
            features[name] = np.frombuffer(value, dtype=np.uint8)
        else:
            features[name] = np.frombuffer(
                str(value).encode("utf-8"), dtype=np.uint8
            )
    return features


def write_recordio_shards_from_iterator(
    records_iter,
    column_names,
    output_dir,
    records_per_shard=8192,
):
    """Reference write_recordio_shards_from_iterator semantics."""
    os.makedirs(output_dir, exist_ok=True)
    files = []
    writer = None
    count = 0
    for row in records_iter:
        if writer is None or count % records_per_shard == 0:
            if writer is not None:
                writer.close()
            path = os.path.join(output_dir, "data-%05d" % len(files))
            files.append(path)
            writer = create_recordio(path)
        writer.write(encode_example(row_to_example(row, column_names)))
        count += 1
    if writer is not None:
        writer.close()
    return files
