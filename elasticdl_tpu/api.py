"""Client API: the ``edl train|evaluate|predict|clean`` implementations.

Parity: reference elasticdl/api.py + client.py — each subcommand parses
its flag set, builds+pushes the job image and submits only the master pod
(which then creates PS/worker pods itself).

TPU-native addition: **local mode**. On a TPU VM there is no need for a
k8s hop — when no ``--docker_image_repository`` is given the job runs
right here: the master (dispatcher + services + RPC) starts in-process
and workers run as local processes under the elastic
LocalInstanceManager (num_workers>0) or inline in this process
(num_workers=0). Same code paths, same elasticity, zero cluster.
"""

import os
import sys

from elasticdl_tpu.common import args as args_module
from elasticdl_tpu.common.args import (
    build_arguments_from_parsed_result,
    parse_envs,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


def train(argv):
    args = args_module.parse_master_args(argv)
    return _run_job(args, argv)


def _serving_job(argv, verb, data_flag):
    """Shared gate + launch for serving-only jobs (evaluate / predict).

    Both need their data flag plus a model source: a pinned checkpoint
    file, or — only on the allreduce plane, whose workers read the
    sharded elastic format — a --checkpoint_dir (the PS-mode master
    initializes solely from --checkpoint_filename_for_init and would
    otherwise score a randomly-initialized model without error). One
    definition of "valid model source" here; Master.__init__ re-checks
    it server-side."""
    if not _has_flag(argv, data_flag):
        print("edl %s requires %s" % (verb, data_flag), file=sys.stderr)
        return 2
    allreduce = _flag_value(argv, "--distribution_strategy") == (
        "AllreduceStrategy"
    )
    if not (
        _has_flag(argv, "--checkpoint_filename_for_init")
        or (allreduce and _has_flag(argv, "--checkpoint_dir"))
    ):
        print(
            "edl %s requires --checkpoint_filename_for_init "
            "(or, under AllreduceStrategy, --checkpoint_dir with "
            "sharded elastic checkpoints)" % verb,
            file=sys.stderr,
        )
        return 2
    argv = list(argv)
    if not _has_flag(argv, "--training_data"):
        argv += ["--training_data", ""]
    args = args_module.parse_master_args(argv)
    return _run_job(args, argv)


def evaluate(argv):
    """Evaluation-only job (reference args.py add_evaluate_params)."""
    return _serving_job(argv, "evaluate", "--validation_data")


def predict(argv):
    """Prediction-only job (reference args.py add_predict_params)."""
    return _serving_job(argv, "predict", "--prediction_data")


def clean(argv):
    import argparse

    parser = argparse.ArgumentParser(description="edl clean")
    args_module.add_clean_params(parser)
    args = parser.parse_args(argv)
    from elasticdl_tpu.image_builder import remove_images

    removed = remove_images(
        docker_image_repository=args.docker_image_repository,
        all_images=args.all,
        docker_base_url=args.docker_base_url,
    )
    logger.info("Removed images: %s", removed)
    return 0


def _has_flag(argv, flag):
    return any(a == flag or a.startswith(flag + "=") for a in argv)


def _flag_value(argv, flag):
    for i, a in enumerate(argv):
        if a == flag:
            return argv[i + 1] if i + 1 < len(argv) else None
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


# -- job execution ----------------------------------------------------------


def _run_job(args, argv):
    if getattr(args, "docker_image_repository", ""):
        return _submit_cluster_job(args, argv)
    return _run_local_job(args)


def _submit_cluster_job(args, argv):
    """Build image, submit the master pod (reference api.py:132-154)."""
    from elasticdl_tpu.common.k8s_client import Client
    from elasticdl_tpu.image_builder import build_and_push_docker_image

    image_name = build_and_push_docker_image(
        model_zoo=args.model_zoo,
        docker_image_repository=args.docker_image_repository,
        base_image=args.image_base,
        extra_pypi=args.extra_pypi_index,
        cluster_spec=args.cluster_spec,
        docker_base_url=args.docker_base_url,
        docker_tlscert=args.docker_tlscert,
        docker_tlskey=args.docker_tlskey,
    )
    # in-image paths replace the client-local ones (reference
    # api.py:157-165 _model_zoo_in_docker/_cluster_spec_def_in_docker)
    relay = build_arguments_from_parsed_result(
        args, filter_args={"model_zoo", "cluster_spec"}
    )
    relay += ["--model_zoo", "/model_zoo"]
    if args.cluster_spec:
        relay += [
            "--cluster_spec",
            "/cluster_spec/" + os.path.basename(args.cluster_spec),
        ]
    container_args = ["-m", "elasticdl_tpu.master.main"] + relay
    client = Client(
        image_name=image_name,
        namespace=args.namespace,
        job_name=args.job_name,
        cluster_spec=args.cluster_spec,
    )
    client.create_master(
        resource_requests=args.master_resource_request,
        resource_limits=args.master_resource_limit,
        args=container_args,
        pod_priority=args.master_pod_priority,
        image_pull_policy=args.image_pull_policy,
        restart_policy=args.restart_policy,
        volume=args.volume,
        envs=parse_envs(args.envs),
    )
    logger.info("Job %s submitted (master pod created).", args.job_name)
    return 0


def _run_local_job(args):
    """Run master + workers on this machine (TPU-VM mode)."""
    from elasticdl_tpu.master.master import Master

    if getattr(args, "port", None) is None:
        args.port = 0  # local mode: bind an ephemeral port
    if getattr(args, "num_ps_pods", 0) > 0:
        # local mode never launches PS processes: every worker talks to
        # the master, so the master must hold the optimizer. With the
        # (cluster-oriented) default num_ps_pods=1 left in place the
        # master would hold none and dense gradients would be rejected.
        logger.info(
            "local mode ignores --num_ps_pods=%d (no local PS fleet); "
            "the master holds the model",
            args.num_ps_pods,
        )
        args.num_ps_pods = 0
    master = Master(args)
    master.prepare()

    if args.num_workers <= 0:
        # single-process: worker drives the in-process servicer directly
        from elasticdl_tpu.common.model_utils import (
            get_dict_from_params_str,
        )

        if args.distribution_strategy == "AllreduceStrategy":
            from elasticdl_tpu.common.constants import JobType

            if master.job_type in (
                JobType.EVALUATION_ONLY,
                JobType.PREDICTION_ONLY,
            ):
                # pure eval/predict: no collective plane — the elastic
                # worker's serving drain scores the saved checkpoint
                from elasticdl_tpu.worker.elastic_allreduce_worker import (
                    ElasticAllReduceWorker,
                )

                worker = ElasticAllReduceWorker(
                    worker_id=0,
                    job_type=master.job_type,
                    minibatch_size=args.minibatch_size,
                    model_zoo=args.model_zoo,
                    model_def=args.model_def,
                    model_params=args.model_params,
                    dataset_fn=args.dataset_fn,
                    loss=args.loss,
                    optimizer=args.optimizer,
                    eval_metrics_fn=args.eval_metrics_fn,
                    stub=master.master_servicer,
                    data_reader_params=get_dict_from_params_str(
                        args.data_reader_params
                    ),
                    checkpoint_dir=getattr(args, "checkpoint_dir", ""),
                    checkpoint_filename_for_init=getattr(
                        args, "checkpoint_filename_for_init", ""
                    ),
                    prediction_outputs_processor=getattr(
                        args,
                        "prediction_outputs_processor",
                        "PredictionOutputsProcessor",
                    ),
                )
                try:
                    worker.run()
                except Exception:
                    # the master would otherwise poll the requeued eval
                    # tasks forever; shut it down, then surface the
                    # worker's error as the job failure
                    master.request_stop()
                    master.run(poll_secs=0.2)
                    raise
                return master.run(poll_secs=0.2)
            from elasticdl_tpu.worker.allreduce_worker import (
                AllReduceWorker,
            )

            AllReduceWorker(
                worker_id=0,
                job_type=master.job_type,
                minibatch_size=args.minibatch_size,
                model_zoo=args.model_zoo,
                model_def=args.model_def,
                model_params=args.model_params,
                dataset_fn=args.dataset_fn,
                loss=args.loss,
                optimizer=args.optimizer,
                eval_metrics_fn=args.eval_metrics_fn,
                stub=master.master_servicer,
                data_reader_params=get_dict_from_params_str(
                    args.data_reader_params
                ),
                accum_steps=getattr(args, "grad_accum_steps", 1),
                precision=getattr(args, "precision_policy", "") or None,
                remat=getattr(args, "remat", ""),
                checkpoint_dir=getattr(args, "checkpoint_dir", ""),
                checkpoint_steps=getattr(args, "checkpoint_steps", 0),
                keep_checkpoint_max=getattr(
                    args, "keep_checkpoint_max", 0
                ),
            ).run()
            return master.run(poll_secs=0.2)

        from elasticdl_tpu.worker.worker import Worker

        worker = Worker(
            worker_id=0,
            job_type=master.job_type,
            minibatch_size=args.minibatch_size,
            model_zoo=args.model_zoo,
            model_def=args.model_def,
            model_params=args.model_params,
            dataset_fn=args.dataset_fn,
            loss=args.loss,
            optimizer=args.optimizer,
            eval_metrics_fn=args.eval_metrics_fn,
            stub=master.master_servicer,
            get_model_steps=args.get_model_steps,
            data_reader_params=get_dict_from_params_str(
                args.data_reader_params
            ),
            precision=getattr(args, "precision_policy", "") or None,
            prediction_outputs_processor=getattr(
                args,
                "prediction_outputs_processor",
                "PredictionOutputsProcessor",
            ),
            telemetry_report_secs=getattr(
                args, "telemetry_report_secs", 5.0
            ),
        )
        from elasticdl_tpu.common.args import warn_accum_unsupported

        warn_accum_unsupported(args, "the in-process PS worker")
        worker.run()
        rc = master.run(poll_secs=0.2)
        return rc

    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )

    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    )
    # local workers all share this host; the allreduce coordinator must
    # advertise an address the sibling processes can dial
    env.setdefault("EDL_COMM_HOST", "localhost")
    # persistent XLA compilation cache shared by every worker process:
    # a relaunched (or standby-promoted) worker re-compiles the same
    # HLO its predecessors already built — with the cache that compile
    # is a disk hit, cutting world re-formation from ~15 s to ~1 s
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(
            os.path.expanduser("~"), ".cache", "elasticdl_tpu", "xla"
        ),
    )

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id",
            str(worker_id),
            "--job_type",
            master.job_type,
            "--master_addr",
            "localhost:%d" % master.port,
        ] + build_arguments_from_parsed_result(
            args,
            filter_args={
                "port",
                "num_workers",
                "training_data",
                "validation_data",
                "prediction_data",
                "job_name",
            },
        )

    manager = LocalInstanceManager(
        master.task_d,
        args.num_workers,
        worker_command,
        restart_policy=args.restart_policy,
        env=env,
        membership=master.membership,
        num_standby=getattr(args, "num_standby_workers", 0),
    )
    master.instance_manager = manager
    manager.start_workers()
    return master.run(poll_secs=1)


# -- CLI --------------------------------------------------------------------

_SUBCOMMANDS = {
    "train": train,
    "evaluate": evaluate,
    "predict": predict,
    "clean": clean,
}


def cli_main(argv):
    """Reference client.py:13-46."""
    if not argv or argv[0] in ("-h", "--help"):
        print(
            "usage: edl {train|evaluate|predict|clean} [flags]",
            file=sys.stderr,
        )
        return 0 if argv else 2
    cmd = argv[0]
    fn = _SUBCOMMANDS.get(cmd)
    if fn is None:
        print("unknown subcommand %r" % cmd, file=sys.stderr)
        return 2
    return fn(argv[1:])
