"""Dynamic micro-batching: shape-bucketed request coalescing + SLO
admission for the serving plane (docs/serving.md, "Micro-batching").

PR 14's scorer answers exactly one request per jitted forward, so
throughput is capped at ``1/forward_latency`` no matter how much
arithmetic intensity the hardware has left — ROADMAP item 3. The
:class:`MicroBatcher` closes that gap the way continuous-batching
servers do (Orca/vLLM, PAPERS.md), re-using the compile plane's
bucketing insight at inference time:

- **Coalesce**: concurrent ``score`` requests of one *shape signature*
  (feature names, dtypes, trailing dims) queue here instead of calling
  :meth:`Scorer.score` inline; a dispatcher thread concatenates them
  into ONE forward. The embedding path amortizes for free — one
  coalesced predict is one id capture, one dedup plan, one PS pull for
  the whole batch, which is where the sparse-model win comes from.
- **Bucket**: batches pad up to a small fixed ladder of row counts
  (powers of two up to ``--serve_max_batch``), so the executable set
  stays bounded and every bucket is pre-warmed on hot swap
  (:meth:`Scorer.set_warm_batch_sizes`) — a version flip never pays a
  first-request compile. Padding REPEATS real rows (never zeros): the
  batch's unique-id set is unchanged, so the dedup plan, the PS pull,
  and PS-side lazy init see exactly the real requests' ids and the
  per-request outputs stay bitwise identical to unbatched scoring.
- **Cutoff**: the oldest queued request bounds the wait — dispatch
  fires at a full bucket OR ``--serve_batch_timeout_ms`` after the
  head enqueued, so a lone request never waits for company.
- **Admit or shed**: past the p99 SLO (``--serve_p99_slo_ms``, fed by
  the existing ``edl_scorer_request_latency_seconds`` histogram) or a
  hard queue-row cap, ``submit`` sheds with :class:`Overloaded` — the
  RPC surface turns that into an explicit ``{"error": "overloaded"}``
  degrade instead of queueing to collapse. The SLO check predicts the
  *completion* time (queued batches ahead x the p99 forward estimate),
  so admission recovers the instant a burst drains.

Concurrency contract (edlint R5/R8, scripts/check.sh): the batcher
lock only guards the queue — jit dispatch (``Scorer.score``) and every
padding copy (concatenate/repeat) run OFF the lock on the dispatcher
thread, and results de-multiplex back to callers through per-request
events. Version swaps need no cooperation: a coalesced forward acquires
its model through the scorer's in-flight ledger like any request, so an
in-flight batch finishes on the version it acquired and ``stop(drain=
True)`` (SIGTERM, docs/serving.md) answers everything already queued
before the thread exits.
"""

import threading
import time

import numpy as np

from elasticdl_tpu.utils import profiling


class Overloaded(RuntimeError):
    """Admission control shed this request (``reason``: ``slo``,
    ``queue_full``, or ``draining``); the RPC reply is the explicit
    ``{"error": "overloaded"}`` degrade, safe to retry elsewhere."""

    def __init__(self, reason):
        super().__init__("overloaded")
        self.reason = reason


def batch_buckets(max_batch):
    """The fixed bucket ladder: powers of two, with ``max_batch``
    itself always the top bucket (pow2 or not)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1, got %d" % max_batch)
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def request_signature(features):
    """``(rows, signature)`` for a feature dict, or ``(None, None)``
    when the request cannot join a coalesced batch (0-d features,
    ragged leading dims, or zero rows). Only same-signature requests
    share a forward: the concatenated batch must be a valid input of
    the same jitted callable."""
    rows = None
    sig = []
    for name in sorted(features):
        a = features[name]
        if getattr(a, "ndim", 0) < 1:
            return None, None
        n = int(a.shape[0])
        if rows is None:
            rows = n
        elif n != rows:
            return None, None
        sig.append((name, str(a.dtype), tuple(a.shape[1:])))
    if not rows:
        return None, None
    return rows, tuple(sig)


def _slice_rows(out, offset, rows):
    """De-multiplex one caller's rows out of a batched output."""
    if isinstance(out, dict):
        return {k: v[offset : offset + rows] for k, v in out.items()}
    return out[offset : offset + rows]


class _Pending:
    """One queued request: features in, (out, version) or err out."""

    __slots__ = (
        "features",
        "rows",
        "sig",
        "t_enq",
        "done",
        "out",
        "version",
        "err",
    )

    def __init__(self, features, rows, sig):
        self.features = features
        self.rows = rows
        self.sig = sig
        self.t_enq = time.monotonic()
        self.done = threading.Event()
        self.out = None
        self.version = -1
        self.err = None


class MicroBatcher:
    """Per-scorer coalescing queue + dispatcher + admission control.

    ``max_batch``: the row budget of one coalesced forward (top of the
    bucket ladder). ``timeout_ms``: latency-budget cutoff measured from
    the oldest queued request. ``p99_slo_ms``: shed when the predicted
    completion time (queue ahead + one forward, at the histogram's p99
    estimate) exceeds this; 0 disables. ``queue_rows``: hard cap on
    queued rows (0 -> ``8 * max_batch``) — the backstop that bounds
    memory and tail latency even before the SLO estimate warms up.
    """

    def __init__(
        self,
        scorer,
        max_batch=64,
        timeout_ms=2.0,
        p99_slo_ms=0.0,
        queue_rows=0,
        slo_refresh_s=0.25,
    ):
        self._scorer = scorer
        self.max_batch = int(max_batch)
        self.buckets = batch_buckets(self.max_batch)
        self._timeout_s = max(0.0, float(timeout_ms) / 1000.0)
        self._slo_s = max(0.0, float(p99_slo_ms) / 1000.0)
        self._queue_rows_cap = (
            int(queue_rows) if queue_rows else 8 * self.max_batch
        )
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._queue = []  # FIFO of _Pending (per-sig order preserved)
        self._queued_rows = 0
        self._dispatching_rows = 0
        self._stopping = False
        self._thread = None
        # p99 estimate cache: the histogram read happens OFF the queue
        # lock (R5) at most once per refresh window, behind its own
        # tiny lock (R8 — the cache tuple is shared across submitters)
        self._est_mu = threading.Lock()
        self._slo_refresh_s = float(slo_refresh_s)
        self._p99_at = -1e9
        self._p99_est = None
        r = profiling.metrics
        self._h_batch = r.histogram(
            "edl_scorer_batch_size",
            "Real (pre-padding) rows per dispatched coalesced forward",
            buckets=tuple(float(b) for b in self.buckets),
        )
        self._c_batches = r.counter(
            "edl_scorer_batches_total",
            "Coalesced forwards dispatched",
        )
        self._c_shed = r.counter(
            "edl_scorer_shed_total",
            "Requests shed by admission control, by reason",
            labels=("reason",),
        )
        r.register_collector(self._collect)

    # -- telemetry -----------------------------------------------------------

    def _collect(self):
        with self._mu:
            depth = len(self._queue)
            rows = self._queued_rows + self._dispatching_rows
        return [
            ("edl_scorer_queue_depth", {}, depth),
            ("edl_scorer_queue_rows", {}, rows),
        ]

    def queue_depth(self):
        """(queued requests, queued+dispatching rows) snapshot."""
        with self._mu:
            return (
                len(self._queue),
                self._queued_rows + self._dispatching_rows,
            )

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        with self._mu:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="edl-micro-batcher"
            )
            self._thread.start()

    def stop(self, drain=True, timeout=30.0):
        """Stop taking requests; with ``drain`` (the SIGTERM path),
        everything already queued is answered before the dispatcher
        exits — otherwise queued requests shed as ``draining``."""
        deadline = time.monotonic() + timeout
        with self._mu:
            self._stopping = True
            if not drain:
                for p in self._queue:
                    p.err = Overloaded("draining")
                dropped, self._queue = self._queue, []
                self._queued_rows = 0
            else:
                dropped = []
            self._cv.notify_all()
            if drain:
                while self._queue or self._dispatching_rows:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cv.wait(left)
            thread, self._thread = self._thread, None
        for p in dropped:
            self._c_shed.inc(reason="draining")
            p.done.set()
        if thread is not None:
            thread.join(
                timeout=max(0.0, deadline - time.monotonic()) + 1.0
            )

    def close(self):
        profiling.metrics.unregister_collector(self._collect)

    # -- the request path ----------------------------------------------------

    def submit(self, features):
        """Score ``features`` through the coalescing queue ->
        ``(output, model_version)``. Raises :class:`Overloaded` when
        admission sheds; un-batchable requests (0-d features, ragged
        leading dims) and a not-started batcher score inline."""
        rows, sig = request_signature(features)
        if rows is None or self._thread is None:
            return self._scorer.score(features)
        p99 = self._forward_p99() if self._slo_s > 0 else None
        p = _Pending(features, rows, sig)
        with self._mu:
            if self._stopping:
                reason = "draining"
            elif self._queued_rows + rows > self._queue_rows_cap:
                reason = "queue_full"
            elif p99 is not None and self._past_slo_locked(rows, p99):
                reason = "slo"
            else:
                reason = None
                self._queue.append(p)
                self._queued_rows += rows
                self._cv.notify_all()
        if reason is not None:
            self._c_shed.inc(reason=reason)
            raise Overloaded(reason)
        p.done.wait()
        if p.err is not None:
            raise p.err
        return p.out, p.version

    def _past_slo_locked(self, rows, p99):
        """Would this request's predicted QUEUE WAIT bust the SLO?
        Batches ahead of it (queued + dispatching, NOT its own rows —
        an idle plane must always admit, even when the histogram's p99
        is poisoned by a cold-compile outlier a cumulative histogram
        never forgets) x the p99 forward estimate; pure arithmetic
        (the histogram read happened off-lock in :meth:`_forward_p99`),
        so it recovers the moment a burst drains instead of echoing
        the burst's tail for minutes."""
        ahead = self._queued_rows + self._dispatching_rows
        batches = (ahead + self.max_batch - 1) // self.max_batch
        return batches * p99 > self._slo_s

    def _forward_p99(self):
        now = time.monotonic()
        with self._est_mu:
            if now - self._p99_at <= self._slo_refresh_s:
                return self._p99_est
        est = self._scorer.latency_p99()
        with self._est_mu:
            self._p99_at = now
            self._p99_est = est
        return est

    # -- the dispatcher thread -----------------------------------------------

    def _run(self):
        while True:
            batch = self._gather()
            if batch is None:
                return
            self._dispatch(batch)

    def _gather(self):
        """Block until a batch is due (full bucket or cutoff expired),
        pop it from the queue, return it. None means shut down."""
        with self._mu:
            while not self._queue:
                if self._stopping:
                    return None
                self._cv.wait()
            head = self._queue[0]
            deadline = head.t_enq + self._timeout_s
            while True:
                take, rows = self._match_locked(head.sig)
                if rows >= self.max_batch or self._stopping:
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            for p in take:
                self._queue.remove(p)
                self._queued_rows -= p.rows
            self._dispatching_rows = rows
            return take, rows

    def _match_locked(self, sig):
        """Oldest-first requests of ``sig`` fitting the row budget
        (the head always ships, even oversize — it pads to the next
        power of two past the ladder rather than starving)."""
        take, rows = [], 0
        for p in self._queue:
            if p.sig != sig:
                continue
            if take and rows + p.rows > self.max_batch:
                break
            take.append(p)
            rows += p.rows
        return take, rows

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        b = self.buckets[-1]
        while b < n:
            b *= 2
        return b

    def _dispatch(self, batch):
        """Assemble, score, de-multiplex — all OFF the queue lock; one
        exception fails every coalesced caller (they see the same
        degraded plane a solo request would)."""
        take, rows = batch
        try:
            feats = self._assemble(take, rows)
            out, version = self._scorer.score(feats)
            self._h_batch.observe(rows)
            self._c_batches.inc()
            offset = 0
            for p in take:
                p.out = _slice_rows(out, offset, p.rows)
                p.version = version
                offset += p.rows
        except Exception as err:  # noqa: BLE001 — reported per caller
            for p in take:
                p.err = err
        finally:
            with self._mu:
                self._dispatching_rows = 0
                self._cv.notify_all()
            for p in take:
                p.done.set()

    def _assemble(self, take, rows):
        """One concatenated feature dict, padded to the bucket by
        repeating real rows (never zeros — keeps the dedup plan's
        unique-id set, and therefore every per-request output, bitwise
        identical to unbatched scoring)."""
        bucket = self.bucket_for(rows)
        pad = bucket - rows
        pad_idx = np.arange(pad) % rows if pad else None
        feats = {}
        for name in take[0].features:
            parts = [np.asarray(p.features[name]) for p in take]
            arr = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if pad:
                arr = np.concatenate([arr, arr[pad_idx]])
            feats[name] = arr
        return feats
