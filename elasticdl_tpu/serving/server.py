"""The scorer's RPC surface: ``score`` + ``scorer_status``.

Same transport stack as every other plane (rpc/core bytes-frame gRPC,
no codegen): requests are dict messages whose non-underscore fields ARE
the feature arrays, replies carry the output array(s) plus the
``model_version`` that scored them. The shared-memory endpoint is
always offered (rpc/shm_transport) so a co-located client's request
payloads ride slots, and every method is instrumented with the
``role="scorer"`` server-latency histogram (docs/observability.md).

Both RPCs are idempotent reads (edlint R9): scoring mutates nothing but
cache residency, so a client may retry a timed-out ``score`` freely —
the serving plane's retry discipline (docs/serving.md). That includes
the micro-batcher's shed reply: ``{"error": "overloaded"}`` is an
explicit degrade BEFORE any work happened, the safest retry there is
(against another scorer, or after backoff).
"""

import threading

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.serving.batcher import Overloaded
from elasticdl_tpu.utils import profiling


class ScorerServicer:
    """Dict-method servicer over one :class:`~elasticdl_tpu.serving.
    scorer.Scorer` — served via rpc.core or called in-process. With a
    :class:`~elasticdl_tpu.serving.batcher.MicroBatcher`, ``score``
    enqueues into the coalescing queue instead of calling the scorer
    inline (docs/serving.md, "Micro-batching")."""

    def __init__(self, scorer, batcher=None):
        self._scorer = scorer
        self._batcher = batcher

    def score(self, req):
        """Score the request's feature arrays.

        Every non-underscore field is a feature (``_sctx`` and friends
        are transport metadata). Replies: ``output`` (single-output
        models) or ``out:<name>`` fields (dict outputs), plus
        ``model_version``. Failures return ``{"error": ...}`` instead
        of a transport error: the request was well-formed, the plane
        is degraded (e.g. the PS fleet is mid-relaunch) or shedding
        (``overloaded`` + ``reason``) — callers gate on the field and
        retry on their own policy."""
        features = {
            k: np.asarray(v)
            for k, v in req.items()
            if not k.startswith("_")
        }
        if not features:
            # counted here, not in Scorer.score: the request never
            # reaches it (the no_model/predict kinds are counted there)
            self._scorer.note_error("bad_request")
            return {"error": "score request carried no feature arrays"}
        try:
            if self._batcher is not None:
                out, version = self._batcher.submit(features)
            else:
                out, version = self._scorer.score(features)
        except Overloaded as err:
            self._scorer.note_error("overloaded")
            return {"error": "overloaded", "reason": err.reason}
        except Exception as err:  # noqa: BLE001 — degraded, reported
            logger.warning("score request failed: %s", err)
            return {"error": str(err)[:500]}
        reply = {"model_version": int(version)}
        if isinstance(out, dict):
            for name, value in out.items():
                reply["out:%s" % name] = np.asarray(value)
        else:
            reply["output"] = np.asarray(out)
        return reply

    def scorer_status(self, req):
        """Read-only probe: current model version, in-flight ledger,
        cache/staleness stats (idempotent, edlint R9)."""
        return self._scorer.status()

    def rpc_methods(self):
        return profiling.instrument_service_methods(
            {
                "score": self.score,
                "scorer_status": self.scorer_status,
            },
            role="scorer",
        )


class ScorerServer:
    """One scorer process's serving stack: RPC + shm + telemetry.

    ``port=0`` binds an ephemeral RPC port (exposed as ``.port``).
    ``telemetry_port >= 0`` serves the PR-6 ``/metrics``/``/events``/
    ``/trace``/``/healthz`` plane (``loading`` 503 until the first
    model installs, then ``serving``, ``draining`` through stop).
    """

    def __init__(self, scorer, port=0, telemetry_port=-1, batcher=None):
        from elasticdl_tpu.rpc.core import serve
        from elasticdl_tpu.rpc.shm_transport import install_shm_endpoint

        self._scorer = scorer
        self._batcher = batcher
        if batcher is not None:
            batcher.start()
        self.servicer = ScorerServicer(scorer, batcher=batcher)
        self._draining = threading.Event()
        self._telemetry_http = None
        if telemetry_port is not None and telemetry_port >= 0:
            from elasticdl_tpu.master.telemetry import (
                ProcessTelemetry,
                TelemetryHTTPServer,
            )

            self._telemetry_http = TelemetryHTTPServer(
                ProcessTelemetry(),
                port=telemetry_port,
                health_fn=self._health,
            )
            self.telemetry_port = self._telemetry_http.port
        methods, self._shm_registry = install_shm_endpoint(
            self.servicer.rpc_methods()
        )
        self._server = serve(methods, port)
        self.port = self._server._edl_port
        logger.info(
            "scorer RPC server on port %d%s",
            self.port,
            (
                " (telemetry on %d)" % self.telemetry_port
                if self._telemetry_http is not None
                else ""
            ),
        )

    def _health(self):
        if self._draining.is_set():
            return "draining"
        return "serving" if self._scorer.model_version >= 0 else "loading"

    def stop(self):
        self._draining.set()
        if self._batcher is not None:
            # drain BEFORE the transport goes: new submits shed as
            # "draining", queued requests get their replies, in-flight
            # batches finish on the version they acquired
            self._batcher.stop(drain=True)
            self._batcher.close()
            self._batcher = None
        if self._server is not None:
            self._server.stop(grace=None)
            self._server = None
        if self._shm_registry is not None:
            self._shm_registry.close()
            self._shm_registry = None
        if self._telemetry_http is not None:
            self._telemetry_http.close()
            self._telemetry_http = None
