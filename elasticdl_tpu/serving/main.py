"""Scorer process entry: one pod of the serving fleet.

Boot order (docs/serving.md): build the PS channels (finite deadline +
bounded idempotent retries + shm negotiation — the serving-plane retry
discipline), share ONE version-tagged hot-row cache between the request
path and the delta sync, start the export-directory watcher (the first
artifact flips /healthz ``loading`` -> ``serving``), then serve. A
scorer never blocks the boot on the trainer: it answers
``scorer_status``/``/healthz`` immediately and ``score`` errors cleanly
until the first export lands.

SIGTERM drains: health flips to ``draining``, the micro-batcher stops
admitting (new submits shed ``draining``) and answers everything
already queued — an in-flight batch finishes on the model version it
acquired — then the RPC plane stops taking requests, sync/watcher
threads join, channels close, exit 0 — scorers are stateless, so there
is nothing to snapshot.
"""

import signal
import sys
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger


def build_scorer(args):
    """Construct the scorer stack from parsed args; returns
    (scorer, watcher, sync, bound_channels, batcher). ``batcher`` is
    None when ``--serve_max_batch <= 1`` (the pre-PR-18 inline path)."""
    from elasticdl_tpu.nn.comm_plane import HotRowCache
    from elasticdl_tpu.serving.batcher import MicroBatcher
    from elasticdl_tpu.serving.delta_sync import EmbeddingDeltaSync
    from elasticdl_tpu.serving.scorer import ModelDirectoryWatcher, Scorer
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient

    bound = []
    ps_client = None
    sync = None
    cache = None
    addrs = [a for a in (args.ps_addrs or "").split(",") if a]
    if addrs:
        cache = HotRowCache(
            args.hot_row_cache_rows,
            window=args.serving_staleness_versions,
        )
        bound = [
            BoundPS(
                addr,
                deadline_s=args.rpc_deadline_s or None,
                retries=args.rpc_retries,
                shm=args.ps_shm,
            )
            for addr in addrs
        ]
        ps_client = PSClient(bound, cache=cache)
    scorer = Scorer(
        ps_client=ps_client,
        staleness_versions=args.serving_staleness_versions,
        model_zoo=args.model_zoo or None,
    )
    watcher = ModelDirectoryWatcher(
        args.export_dir,
        scorer,
        interval_s=args.watch_interval_s,
        model_zoo=args.model_zoo or None,
    )
    if ps_client is not None:
        sync = EmbeddingDeltaSync(
            ps_client,
            cache,
            interval_s=args.serving_sync_interval_s,
        )
    batcher = None
    if args.serve_max_batch > 1:
        batcher = MicroBatcher(
            scorer,
            max_batch=args.serve_max_batch,
            timeout_ms=args.serve_batch_timeout_ms,
            p99_slo_ms=args.serve_p99_slo_ms,
            queue_rows=args.serve_queue_rows,
        )
        # hot swaps pre-trace every bucket shape, never a request
        scorer.set_warm_batch_sizes(batcher.buckets)
    return scorer, watcher, sync, bound, batcher


def main():
    from elasticdl_tpu.common.args import parse_scorer_args
    from elasticdl_tpu.common.jax_platform import honor_jax_platforms_env
    from elasticdl_tpu.serving.server import ScorerServer
    from elasticdl_tpu.utils import profiling

    honor_jax_platforms_env()
    args = parse_scorer_args()
    profiling.spans.set_process("scorer-%d" % args.scorer_id)
    profiling.maybe_arm_flight_recorder()

    scorer, watcher, sync, bound, batcher = build_scorer(args)
    server = ScorerServer(
        scorer,
        port=args.port,
        telemetry_port=args.scorer_telemetry_port,
        batcher=batcher,
    )
    watcher.start()
    if sync is not None:
        sync.start()

    stop = threading.Event()

    def _drain(signum, frame):
        if stop.is_set():
            return
        logger.warning("SIGTERM: draining the scorer")
        stop.set()

    signal.signal(signal.SIGTERM, _drain)
    try:
        while not stop.wait(1.0):
            pass
    except KeyboardInterrupt:
        logger.warning("scorer stopping")
    finally:
        server.stop()
        watcher.stop()
        if sync is not None:
            sync.stop()
        scorer.close()
        for channel in bound:
            channel.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
