"""The serving plane (docs/serving.md): a scorer fleet answering
inference traffic from the latest exported dense graph plus embeddings
served read-through from the live PS fleet, freshness bounded by
version-tagged deltas — the serve third of the streaming
train -> export -> serve loop."""

from elasticdl_tpu.serving.delta_sync import EmbeddingDeltaSync
from elasticdl_tpu.serving.scorer import (
    ModelDirectoryWatcher,
    Scorer,
    ScorerModel,
)
from elasticdl_tpu.serving.server import ScorerServer, ScorerServicer

__all__ = [
    "EmbeddingDeltaSync",
    "ModelDirectoryWatcher",
    "Scorer",
    "ScorerModel",
    "ScorerServer",
    "ScorerServicer",
]
