"""Version-tagged delta sync: keeps a scorer's hot-row cache fresh.

The freshness half of the serving plane (docs/serving.md). A scorer
serves embedding rows read-through from the live PS fleet via the
plane-shared :class:`~elasticdl_tpu.nn.comm_plane.HotRowCache`, whose
window mechanically bounds every HIT to at most
``--serving_staleness_versions`` shard versions behind the newest
version this process has seen. Without a delta feed that bound is
enforced by ATTRITION: every version advance ages every cached entry of
the shard, so under continuous training the whole cache churns each
window — a permanent miss storm on exactly the power-law head rows the
cache exists for. This sync loop turns the bound into cheap bookkeeping:

- poll each shard's ``serving_status`` (per-table newest update
  version + this incarnation's ``shard_epoch``),
- for tables that advanced, ``pull_embedding_delta`` names exactly the
  row ids that moved; :meth:`HotRowCache.refresh_table` drops the
  cached copies of THOSE rows (optionally re-pulling them hot) and
  re-tags every other entry fresh — rows the PS proves unchanged never
  churn,
- tables that did NOT advance re-tag wholesale (a recorded-update-free
  interval is a proof of no movement: lazy init happens before any
  cache copy exists, and every apply is noted),
- an incomplete delta (the shard pruned past our sync point) falls
  back to :meth:`HotRowCache.invalidate_table` — only that table's
  stale rows drop, never the co-sharded tables' (the PR-15 cache fix),
- a changed ``shard_epoch`` means the shard relaunched: the PSClient
  reconnect protocol (docs/ps_recovery.md) already invalidated the
  shard's entries inside ``serving_status``'s reply handling; the sync
  just re-baselines.

Retry discipline (the PR-12 failover posture, scaled to a data plane):
both RPCs are idempotent reads (edlint R9), so the scorer's channel may
retry them freely — the process entry builds its ``BoundPS`` channels
with a finite deadline and bounded UNAVAILABLE retries — and the sync
loop itself backs off with capped doubling while a whole round fails,
so a dead fleet costs a bounded poll rate, not a spin.
"""

import threading

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger


class EmbeddingDeltaSync:
    """Background per-shard delta poller over one PSClient + cache.

    ``ps_client``: a :class:`~elasticdl_tpu.worker.ps_client.PSClient`
    whose ``serving_status``/``pull_embedding_delta`` wrappers ride the
    reconnect protocol. ``cache``: the scorer's shared
    :class:`HotRowCache` (usually the same instance the client pulls
    through). ``refresh_rows`` re-pulls dropped-but-hot rows in the
    same sync round so head rows stay resident across updates.
    """

    def __init__(
        self,
        ps_client,
        cache,
        interval_s=0.5,
        max_interval_s=8.0,
        refresh_rows=True,
    ):
        self._client = ps_client
        self._cache = cache
        self._interval = float(interval_s)
        self._max_interval = max(float(max_interval_s), self._interval)
        self._refresh_rows = bool(refresh_rows)
        self._mu = threading.Lock()
        self._synced = {}  # shard -> {table: newest reflected update version}
        self._epochs = {}  # shard -> last seen shard_epoch
        self._stop = threading.Event()
        self._thread = None
        # observability (scraped via the scorer's collector too)
        self.rounds = 0
        self.rows_dropped = 0
        self.rows_retagged = 0
        self.rows_refreshed = 0
        self.tables_invalidated = 0
        self.last_error = None

    # -- one synchronous round (tests drive this directly) ------------------

    def sync_once(self):
        """Sync every shard once; returns {shards_ok, shards_failed}.

        Public on purpose (tests and a one-shot warmer drive it), so it
        is concurrent with the background loop by edlint R8's model —
        every mutable field it touches rides ``_mu``."""
        ok = failed = 0
        for shard in range(self._client.num_ps):
            try:
                self._sync_shard(shard)
                ok += 1
            except Exception as err:  # noqa: BLE001 — counted, backoff
                failed += 1
                with self._mu:
                    self.last_error = str(err)
                logger.debug(
                    "delta sync of shard %d failed (will retry on the "
                    "backed-off cadence): %s",
                    shard,
                    err,
                )
        with self._mu:
            self.rounds += 1
        return {"shards_ok": ok, "shards_failed": failed}

    def _sync_point(self, shard, epoch, table):
        """Read (and baseline) one table's sync point under the lock;
        an epoch change re-baselines the whole shard first — the
        reconnect protocol (PSClient._note_shard_reply inside
        ``serving_status``) already ran the PR-10 shard-selective cache
        invalidation, and the dead incarnation's version clock means
        nothing to the restored one."""
        with self._mu:
            if self._epochs.get(shard) != epoch:
                self._epochs[shard] = epoch
                self._synced[shard] = {}
            return self._synced.setdefault(shard, {}).get(table)

    def _set_sync_point(self, shard, table, version):
        with self._mu:
            self._synced.setdefault(shard, {})[table] = int(version)

    def _count(self, **deltas):
        with self._mu:
            for field, n in deltas.items():
                setattr(self, field, getattr(self, field) + n)

    def _sync_shard(self, shard):
        status = self._client.serving_status(shard)
        epoch = status.get("shard_epoch")
        shard_version = int(status.get("version", -1))
        for table, last in status["tables"].items():
            prev = self._sync_point(shard, epoch, table)
            if prev is None:
                # baseline: entries cached before this point carry
                # pull-time tags; refresh_table's drop-below-since rule
                # retires any the next delta cannot vouch for
                prev = int(last)
                self._set_sync_point(shard, table, prev)
            changed = np.zeros((0,), np.int64)
            covered = prev
            if int(last) > prev:
                ids, covered, complete = self._client.pull_embedding_delta(
                    shard, table, prev
                )
                if not complete:
                    # the shard pruned past our sync point: everything
                    # this table cached below its newest update version
                    # is suspect — drop ONLY this table's stale rows
                    dropped = self._cache.invalidate_table(
                        table, below_version=covered
                    )
                    self._count(
                        tables_invalidated=1, rows_dropped=dropped
                    )
                    self._set_sync_point(shard, table, covered)
                    continue
                changed = ids
            # re-tag up to the SHARD version, not just the table's
            # newest update: ``last`` is the newest version that
            # touched this table, so its rows are provably unchanged
            # through shard_version >= last — without this, a quiet
            # table's entries would age out on the other tables'
            # version advances (the miss storm the delta feed exists
            # to prevent)
            dropped_ids, retagged = self._cache.refresh_table(
                table,
                shard,
                max(shard_version, int(covered)),
                changed,
                since=prev,
            )
            self._count(
                rows_dropped=len(dropped_ids), rows_retagged=retagged
            )
            self._set_sync_point(shard, table, covered)
            if self._refresh_rows and dropped_ids:
                # the dropped rows were HOT (cached); re-pull them in
                # this round so the next request hits — the pull path
                # re-inserts them tagged with its reply version
                self._client.pull_embedding_vectors(
                    table, np.asarray(dropped_ids, dtype=np.int64)
                )
                self._count(rows_refreshed=len(dropped_ids))
        # advance the cache's aging clock from the poll too: with the
        # live entries just re-tagged, aging against the real shard
        # version keeps the staleness bound honest even while no
        # request-path pull is observing versions
        if shard_version >= 0 and self._cache is not None:
            self._cache.note_version(shard, shard_version)

    def synced_versions(self):
        """{shard: {table: version}} snapshot (tests/telemetry)."""
        with self._mu:
            return {s: dict(t) for s, t in self._synced.items()}

    # -- the background loop -------------------------------------------------

    def start(self):
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="edl-delta-sync"
            )
            self._thread.start()

    def _run(self):
        interval = self._interval
        while not self._stop.wait(interval):
            try:
                result = self.sync_once()
            except Exception:  # noqa: BLE001 — loop must survive
                logger.warning("delta sync round failed", exc_info=True)
                result = {"shards_ok": 0}
            if result.get("shards_ok"):
                interval = self._interval
            else:
                # capped doubling while the whole fleet is unreachable
                # (the PR-12 posture: ride the outage out, bounded)
                interval = min(interval * 2.0, self._max_interval)

    def stop(self):
        self._stop.set()
        with self._mu:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    def staleness_gauge(self):
        """Scrape-time staleness reading for the scorer's collector."""
        return self._cache.max_live_lag() if self._cache is not None else 0
