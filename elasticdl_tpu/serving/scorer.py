"""The scorer: inference over exported dense graphs + live embeddings.

The serving plane's worker (docs/serving.md). One scorer process
answers inference requests from:

- the **latest exported dense graph** — an export artifact
  (common/export.py, loaded through its ``MANIFEST.json``), either the
  source-free ``serving_fn.jaxexport`` plane or the model rebuilt from
  the manifest's provenance metadata, jitted ONCE per model_version,
- **embeddings served read-through from the PS fleet** via the shared
  :class:`~elasticdl_tpu.nn.comm_plane.CommPlane` +
  :class:`~elasticdl_tpu.nn.comm_plane.HotRowCache`, kept fresh by
  :class:`~elasticdl_tpu.serving.delta_sync.EmbeddingDeltaSync` so a
  served row is never more than ``--serving_staleness_versions`` shard
  versions behind.

Hot swap: :class:`ModelDirectoryWatcher` notices a new export version,
loads AND WARMS it off the request path (the jit compile happens on the
watcher thread against the last request's feature shapes), then
:meth:`Scorer.install` flips the double buffer — new requests route to
the new executable immediately, requests already in flight finish on
the version they started with, and the old model object drops once its
in-flight count drains to zero.
"""

import os
import threading
import time

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.utils import profiling


def _resize_rows(template, rows):
    """The features template re-tiled to ``rows`` leading rows — how
    warm-on-swap reaches every micro-batching bucket shape. np.resize
    repeats cyclically; the values are zeros and never matter, only
    the traced shapes/dtypes."""
    import jax

    return jax.tree_util.tree_map(
        lambda a: np.resize(a, (int(rows),) + a.shape[1:])
        if a.ndim >= 1
        else a,
        template,
    )


def _template_rows(template):
    """Leading row count of a features template (None when 0-d)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(template):
        if getattr(leaf, "ndim", 0) >= 1:
            return int(leaf.shape[0])
    return None


# Rebuilt models + their jitted forwards, shared ACROSS artifact
# versions: a streaming trainer exports the same model config every
# cadence point, and a fresh jit per version would recompile an
# identical executable on every hot swap — the params are call
# ARGUMENTS, not baked constants, so one traced callable serves every
# version of one provenance. Keyed by (model_zoo, model_def,
# model_params); a handful of entries per process, never pruned.
_REBUILD_CACHE = {}
_REBUILD_MU = threading.Lock()


class ScorerModel:
    """One export artifact, loaded and ready to score.

    Dense-only models serve through the artifact's serialized
    ``serving_fn`` when present (source-free), else through a jitted
    forward of the model rebuilt from ``metadata['model_def']``.
    Elastic-embedding models always rebuild (their lookup leaves the
    graph by design) and score through the same hoisted-lookup path the
    trainer uses: capture ids -> dedup plan -> read-through pull ->
    static-bucket scatter -> jitted apply (docs/embedding_planes.md).
    The rebuilt module and its jitted forward are shared across
    versions of the same provenance (see ``_REBUILD_CACHE``), so a hot
    swap costs one params load — never a recompile.
    """

    def __init__(self, export_dir, model_zoo=None):
        from elasticdl_tpu.common.export import load_export

        self.export_dir = os.path.abspath(export_dir)
        self.exported = load_export(export_dir)
        self.version = int(self.exported.version)
        self._model_zoo = model_zoo
        self._mu = threading.Lock()
        self._prepared = False
        self._model = None
        self._forward = None
        self._emb_forward = None
        self._embedding_dims = {}  # {path_tuple: dim}
        self._embedding_initializers = {}
        self._num_calls = 0
        self._plan_lookup_multi = None

    @property
    def params(self):
        return self.exported.params

    @property
    def embedding_tables(self):
        """{table_name: (dim, initializer)} after :meth:`prepare` —
        what an uninitialized-relaunch re-push needs (docs/serving.md).
        """
        from elasticdl_tpu.nn.embedding import path_name

        return {
            path_name(path): (
                dim,
                self._embedding_initializers.get(path, "uniform"),
            )
            for path, dim in self._embedding_dims.items()
        }

    def _rebuild(self):
        """Build the model object from the manifest's provenance."""
        from elasticdl_tpu.common.model_utils import get_model_spec

        meta = self.exported.metadata
        model_def = meta.get("model_def")
        if not model_def:
            raise ValueError(
                "export at %s carries no model_def metadata and no "
                "serving function; nothing to rebuild" % self.export_dir
            )
        spec = get_model_spec(
            model_zoo=self._model_zoo or meta.get("model_zoo"),
            model_def=model_def,
            model_params=meta.get("model_params") or None,
        )
        return spec.model

    def _rebuild_key(self):
        meta = self.exported.metadata
        return (
            self._model_zoo or meta.get("model_zoo"),
            meta.get("model_def"),
            meta.get("model_params") or "",
        )

    def prepare(self, features):
        """Discover the embedding surface + bind the jitted forward.

        Lazy (the artifact does not record feature shapes); runs once
        per ScorerModel, and the expensive half — rebuild + capture
        discovery + jit — once per PROVENANCE: later versions of the
        same model config bind the cached module/forward and pay only
        their params load. Thread-safe: the watcher warms on its own
        thread while the server may race a first request in.
        """
        with self._mu:
            if self._prepared:
                return
            if self.exported.has_serving_fn():
                # source-free plane: serialized StableHLO, already
                # batch-polymorphic — no rebuild, no embedding surface
                self._prepared = True
                return
            key = self._rebuild_key()
            with _REBUILD_MU:
                entry = _REBUILD_CACHE.get(key)
            if entry is None:
                entry = self._build_entry(features)
                with _REBUILD_MU:
                    # racing builders converge; the first stays (its
                    # jitted callable may already hold warm traces)
                    entry = _REBUILD_CACHE.setdefault(key, entry)
            self._model = entry["model"]
            self._embedding_dims = entry["embedding_dims"]
            self._embedding_initializers = entry["embedding_initializers"]
            self._num_calls = entry["num_calls"]
            self._emb_forward = entry["emb_forward"]
            self._forward = entry["forward"]
            self._prepared = True

    def _build_entry(self, features):
        """The once-per-provenance build: rebuild the module, discover
        the embedding surface with one capture pass, jit the forward.
        The capture only needs the params' STRUCTURE, identical across
        versions of one provenance."""
        from elasticdl_tpu.nn.embedding import capture_embedding_ids
        from elasticdl_tpu.training.step import (
            make_embedding_forward_fn,
            make_forward_fn,
        )

        model = self._rebuild()
        layer_info = {}
        captured = capture_embedding_ids(
            model,
            {"params": self.params},
            features,
            layer_info=layer_info,
        )
        embedding_dims = {
            path: info[0] for path, info in layer_info.items()
        }
        return {
            "model": model,
            "embedding_dims": embedding_dims,
            "embedding_initializers": {
                path: info[1] for path, info in layer_info.items()
            },
            "num_calls": sum(len(v) for v in captured.values()),
            "emb_forward": (
                make_embedding_forward_fn(model)
                if embedding_dims
                else None
            ),
            "forward": (
                make_forward_fn(model) if not embedding_dims else None
            ),
        }

    def predict(self, features, plane=None, capture_lock=None):
        """Score one features batch; returns the model output.

        ``plane``: the CommPlane serving PS-resident tables (required
        for elastic-embedding models). ``capture_lock``: serializes the
        host-side flax id capture — the interceptor must not run
        concurrently with another capture or an untraced forward
        (worker/worker.py runs it worker-thread-only for the same
        reason); the jitted forward itself runs outside it.
        """
        if not self._prepared:
            self.prepare(features)
        if self.exported.has_serving_fn():
            return self.exported.serve(features)
        if not self._embedding_dims:
            return self._forward(self.params, {}, features)
        if plane is None:
            raise RuntimeError(
                "model %s has PS-resident embedding tables; the scorer "
                "needs a comm plane over the PS fleet to serve them"
                % self.export_dir
            )
        from elasticdl_tpu.nn.embedding import (
            build_collection,
            call_slot_name,
            capture_embedding_ids,
            path_name,
        )

        lock = capture_lock if capture_lock is not None else self._mu
        with lock:
            captured = capture_embedding_ids(
                self._model,
                {"params": self.params},
                features,
                expected_count=self._num_calls,
            )
            lookups = {
                path: plane.plan_lookup_multi(ids_list)
                for path, ids_list in captured.items()
            }
        pulled = plane.pull(
            {
                path_name(path): unique
                for path, (unique, _, _) in lookups.items()
            }
        )
        rows_by_path, idx_by_path = {}, {}
        for path, (unique, idxs, bucket) in lookups.items():
            rows_by_path[path] = plane.scatter(
                pulled[path_name(path)], bucket
            )
            for i, idx in enumerate(idxs):
                idx_by_path[path + (call_slot_name(i),)] = idx
        return self._emb_forward(
            self.params,
            build_collection(rows_by_path, "rows"),
            {},
            build_collection(idx_by_path, "idx"),
            features,
        )


class Scorer:
    """The double-buffered scoring surface over one model slot.

    Owns the request path's shared machinery: the comm plane (a
    :class:`PsPlane` over the caller's PSClient), the capture lock, the
    in-flight ledger the hot swap drains against, and the process
    telemetry (request-latency histogram, error counters, and a
    scrape-time collector for the staleness gauge / cache hit rate /
    current model version).
    """

    def __init__(
        self,
        ps_client=None,
        staleness_versions=None,
        model_zoo=None,
    ):
        from elasticdl_tpu.nn.comm_plane import PsPlane

        self._client = ps_client
        self._plane = PsPlane(ps_client) if ps_client is not None else None
        self._model_zoo = model_zoo
        self._mu = threading.Lock()
        self._capture_mu = threading.Lock()
        self._current = None
        self._inflight = {}  # model_version -> in-flight request count
        self._draining = {}  # model_version -> ScorerModel awaiting drain
        self._drained = threading.Condition(self._mu)
        self._features_template = None
        self._warm_batch_sizes = ()
        self._swaps = 0
        cache = ps_client.hot_row_cache if ps_client is not None else None
        self._cache = cache
        self._staleness_versions = (
            staleness_versions
            if staleness_versions is not None
            else (cache._window if cache is not None else 0)
        )
        if ps_client is not None and hasattr(
            ps_client, "set_on_shard_reset"
        ):
            # uninitialized PS relaunch (no snapshot): re-push the
            # embedding TABLE INFOS so read-through pulls lazily re-init
            # rows instead of erroring forever; the trainer re-pushes
            # the authoritative state on its own schedule
            # (docs/ps_recovery.md)
            ps_client.set_on_shard_reset(self._on_ps_shard_reset)
        r = profiling.metrics
        self._h_latency = r.histogram(
            "edl_scorer_request_latency_seconds",
            "Scorer-observed request latency (score path, successes "
            "only)",
        )
        self._c_requests = r.counter(
            "edl_scorer_requests_total",
            "Score requests by outcome",
            labels=("outcome",),
        )
        self._c_errors = r.counter(
            "edl_scorer_errors_total",
            "Degraded-path score failures by kind — the reply-payload "
            "errors /metrics previously could not alert on",
            labels=("kind",),
        )
        r.register_collector(self._collect)

    # -- telemetry -----------------------------------------------------------

    def _collect(self):
        """Scrape-time gauges: staleness (the serving freshness
        contract, docs/serving.md), cache hit rate, model version."""
        out = []
        if self._cache is not None:
            out.append(
                (
                    "edl_scorer_row_staleness_versions",
                    {},
                    self._cache.max_live_lag(),
                )
            )
            probes = self._cache.hits + self._cache.misses
            out.append(
                (
                    "edl_scorer_hot_row_hit_rate",
                    {},
                    (self._cache.hits / probes) if probes else 0.0,
                )
            )
            # per-table cache counters (docs/tiered_store.md): which
            # table's working set the read-through tier is churning
            table_stats = getattr(self._cache, "table_stats", None)
            if table_stats is not None:
                for table, stats in table_stats().items():
                    labels = {"table": table}
                    out.append(
                        ("edl_cache_hits_total", labels, stats["hits"])
                    )
                    out.append(
                        (
                            "edl_cache_misses_total",
                            labels,
                            stats["misses"],
                        )
                    )
                    out.append(
                        (
                            "edl_cache_evictions_total",
                            labels,
                            stats["evictions"],
                        )
                    )
        with self._mu:
            version = (
                self._current.version if self._current is not None else -1
            )
            draining = len(self._draining)
            swaps = self._swaps
        out.append(("edl_scorer_model_version", {}, version))
        out.append(("edl_scorer_draining_versions", {}, draining))
        out.append(("edl_scorer_model_swaps_total", {}, swaps))
        return out

    def note_error(self, kind):
        """Count a degraded-path failure under a bounded ``kind`` label
        (``bad_request``/``no_model``/``overloaded``/``predict``) so
        /metrics can alert on reply-payload errors."""
        self._c_errors.inc(kind=kind)

    def latency_p99(self):
        """p99 estimate (seconds) from the request-latency histogram —
        what the micro-batcher's SLO admission control feeds on; None
        until the first success lands."""
        return self._h_latency.quantile(0.99)

    def close(self):
        profiling.metrics.unregister_collector(self._collect)

    def _on_ps_shard_reset(self, shards):
        model = self.model()
        if model is None:
            return
        tables = model.embedding_tables
        if not tables:
            return
        from elasticdl_tpu.ps.parameters import EmbeddingTableInfo

        logger.warning(
            "re-pushing embedding table infos after PS shard(s) %s "
            "relaunched without restorable state",
            shards,
        )
        self._client.push_embedding_info(
            [
                EmbeddingTableInfo(name, dim, init)
                for name, (dim, init) in sorted(tables.items())
            ]
        )

    # -- the double buffer ---------------------------------------------------

    def model(self):
        with self._mu:
            return self._current

    @property
    def model_version(self):
        with self._mu:
            return (
                self._current.version if self._current is not None else -1
            )

    def set_warm_batch_sizes(self, sizes):
        """Row counts :meth:`install` warms in addition to the last
        request's own shape — the micro-batcher registers its bucket
        ladder here so a hot swap pre-traces EVERY bucket and no
        post-swap batch pays a first-request compile."""
        with self._mu:
            self._warm_batch_sizes = tuple(
                sorted({int(s) for s in sizes if int(s) > 0})
            )

    def install(self, model, warm=True):
        """Swap the serving model to ``model`` (idempotent on version).

        ``warm`` pre-traces the new executable against the last
        request's feature shapes — and every registered micro-batching
        bucket (:meth:`set_warm_batch_sizes`) — BEFORE the flip, so no
        request ever pays the per-version jit compile; the capture lock
        is held through the prepare because a first trace runs the
        module body on the tracing thread (docs/serving.md). In-flight
        requests keep the model they acquired; the superseded version
        drops from the ledger when its count drains to zero.
        """
        with self._mu:
            template = self._features_template
            warm_sizes = self._warm_batch_sizes
        if warm and template is not None:
            t_rows = _template_rows(template)
            sizes = [None]  # the template's own shape, always
            if t_rows is not None and warm_sizes:
                sizes = sorted(set(warm_sizes) | {t_rows})
            try:
                with self._capture_mu:
                    model.prepare(template)
                for n in sizes:
                    shaped = (
                        template
                        if n is None or n == t_rows
                        else _resize_rows(template, n)
                    )
                    model.predict(
                        shaped,
                        plane=self._plane,
                        capture_lock=self._capture_mu,
                    )
            except Exception:  # noqa: BLE001 — warm is best-effort
                logger.warning(
                    "warming export v%d failed; first request pays "
                    "the compile",
                    model.version,
                    exc_info=True,
                )
        with self._mu:
            old = self._current
            if old is not None and old.version == model.version:
                return False
            self._current = model
            self._swaps += 1
            old_inflight = (
                self._inflight.get(old.version, 0)
                if old is not None
                else 0
            )
            if old_inflight:
                self._draining[old.version] = old
        profiling.events.emit(
            "scorer_model_swap",
            version=model.version,
            previous=old.version if old is not None else None,
            export_dir=model.export_dir,
        )
        logger.info(
            "scorer now serving model v%d (%s)%s",
            model.version,
            model.export_dir,
            (
                "; v%d draining %d in-flight request(s)"
                % (old.version, old_inflight)
            )
            if old_inflight
            else "",
        )
        return True

    def _acquire(self):
        with self._mu:
            model = self._current
            if model is None:
                raise RuntimeError(
                    "scorer has no model yet (no export artifact "
                    "loaded); is the trainer exporting?"
                )
            self._inflight[model.version] = (
                self._inflight.get(model.version, 0) + 1
            )
            return model

    def _release(self, model):
        with self._mu:
            n = self._inflight.get(model.version, 1) - 1
            if n > 0:
                self._inflight[model.version] = n
                return
            self._inflight.pop(model.version, None)
            drained = self._draining.pop(model.version, None)
            self._drained.notify_all()
        if drained is not None:
            profiling.events.emit(
                "scorer_version_drained", version=model.version
            )

    def inflight_versions(self):
        """{model_version: in-flight count} snapshot (tests/status)."""
        with self._mu:
            return dict(self._inflight)

    def wait_drained(self, version, timeout=10.0):
        """Block until no request of ``version`` is in flight."""
        deadline = time.monotonic() + timeout
        with self._mu:
            while self._inflight.get(version):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._drained.wait(left)
            return True

    # -- the request path ----------------------------------------------------

    def score(self, features):
        """Score one batch -> (output, model_version)."""
        try:
            model = self._acquire()
        except Exception:
            self._c_requests.inc(outcome="error")
            self.note_error("no_model")
            raise
        try:
            with self._mu:
                need_template = self._features_template is None
            if need_template:
                # shapes-only template for warming future versions
                # (zeros: the values never matter, only the traced
                # shapes/dtypes); built outside the ledger lock, racing
                # writers converge on equivalent templates
                import jax

                template = jax.tree_util.tree_map(
                    lambda a: np.zeros_like(np.asarray(a)), features
                )
                with self._mu:
                    if self._features_template is None:
                        self._features_template = template
            t0 = time.perf_counter()
            out = model.predict(
                features, plane=self._plane, capture_lock=self._capture_mu
            )
            self._h_latency.observe(time.perf_counter() - t0)
            self._c_requests.inc(outcome="ok")
            return out, model.version
        except Exception:
            self._c_requests.inc(outcome="error")
            self.note_error("predict")
            raise
        finally:
            self._release(model)

    def status(self):
        cache = self._cache
        with self._mu:
            version = (
                self._current.version if self._current is not None else -1
            )
            inflight = {str(v): n for v, n in self._inflight.items()}
            swaps = self._swaps
        out = {
            "model_version": version,
            "inflight": inflight,
            "swaps": swaps,
        }
        if cache is not None:
            out["cache_hits"] = cache.hits
            out["cache_misses"] = cache.misses
            out["staleness_versions"] = cache.max_live_lag()
            out["staleness_window"] = self._staleness_versions
        return out


class ModelDirectoryWatcher:
    """Polls an export root for new versioned artifacts and hot-swaps.

    The trainer's streaming export cadence writes
    ``<root>/<subdir>/MANIFEST.json`` last and atomically, so a
    manifest's presence marks a complete artifact (docs/export.md); the
    watcher reads every manifest's ``model_version`` cheaply, loads the
    newest unseen one on ITS thread (orbax restore + jit warm — never
    on a request), and installs it. A directory vanishing mid-load (the
    trainer's retention pruning) just logs and retries next poll.
    """

    def __init__(self, export_root, scorer, interval_s=1.0, model_zoo=None):
        self._root = os.path.abspath(export_root)
        self._scorer = scorer
        self._interval = float(interval_s)
        self._model_zoo = model_zoo
        self._stop = threading.Event()
        self._mu = threading.Lock()
        self._thread = None
        self._failed = {}  # export_dir -> failure count (skip repeats)

    def newest_manifest(self):
        """(export_dir, model_version) of the newest complete artifact
        under the root, or (None, -1)."""
        import json

        best_dir, best_version = None, -1
        try:
            entries = sorted(os.listdir(self._root))
        except OSError:
            return None, -1
        for name in entries:
            path = os.path.join(self._root, name)
            manifest = os.path.join(path, "MANIFEST.json")
            try:
                with open(manifest) as f:
                    version = int(json.load(f).get("model_version", -1))
            except (OSError, ValueError):
                continue  # incomplete/foreign/vanished — not an artifact
            if version > best_version:
                best_dir, best_version = path, version
        return best_dir, best_version

    def poll_once(self):
        """Load+install the newest unseen export; returns its version
        or None when nothing new."""
        path, version = self.newest_manifest()
        with self._mu:
            # drop failure records for pruned artifacts — a long-lived
            # scorer against an every-few-seconds export cadence must
            # not accumulate one dead key per vanished directory
            for stale in [
                p for p in self._failed if not os.path.isdir(p)
            ]:
                del self._failed[stale]
        if path is None or version <= self._scorer.model_version:
            return None
        with self._mu:
            if self._failed.get(path, 0) >= 3:
                return None  # poisoned artifact: stop re-loading it
        try:
            model = ScorerModel(path, model_zoo=self._model_zoo)
            self._scorer.install(model)
        except Exception:  # noqa: BLE001 — keep serving the old version
            with self._mu:
                self._failed[path] = self._failed.get(path, 0) + 1
            logger.warning(
                "loading export at %s failed; still serving v%d",
                path,
                self._scorer.model_version,
                exc_info=True,
            )
            return None
        return version

    def start(self):
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="edl-model-watcher"
            )
            self._thread.start()

    def _run(self):
        while not self._stop.wait(self._interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — loop must survive
                logger.warning("model watcher poll failed", exc_info=True)

    def stop(self):
        self._stop.set()
        with self._mu:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)
