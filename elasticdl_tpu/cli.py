"""`edl` console entry point: train | evaluate | predict | clean.

Parity: reference elasticdl/python/elasticdl/client.py:13-46. The
subcommand implementations live in elasticdl_tpu.api: cluster submission
(image build + master pod) when ``--docker_image_repository`` is set,
else the local mode (master + workers as processes on this TPU VM). This
shim stays import-light so failures surface as a clear message, not a
ModuleNotFoundError.
"""

import sys


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    try:
        from elasticdl_tpu.common.jax_platform import (
            honor_jax_platforms_env,
        )

        honor_jax_platforms_env()
    except ImportError:
        pass  # the api import below reports the broken build
    try:
        from elasticdl_tpu import api
    except ImportError:
        print(
            "elasticdl_tpu client API is not available in this build",
            file=sys.stderr,
        )
        return 2
    return api.cli_main(argv)


if __name__ == "__main__":
    sys.exit(main())
