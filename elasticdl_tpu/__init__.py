"""elasticdl_tpu: a TPU-native elastic distributed training framework.

Re-implements the capabilities of ElasticDL (reference:
elasticdl/python/* in typhoonzero/elasticdl) with an idiomatic
JAX/XLA/pjit design:

- dynamic task dispatch for elasticity (master/task_dispatcher.py)
- jitted ``value_and_grad`` worker steps; sync data parallelism is an
  in-step XLA collective over a ``jax.sharding.Mesh`` instead of a gRPC
  parameter-server round trip
- row-sharded sparse embedding tables in device HBM with all-to-all
  lookup/update (parallel/embedding_sharding.py)
- host-level gRPC control plane for tasks/eval/checkpoint triggers
"""

__version__ = "0.1.0"
