"""Honor an explicit ``JAX_PLATFORMS`` env var in every process entry.

A sitecustomize may pre-register an accelerator PJRT plugin and pin
``jax_platforms`` through jax.config at interpreter startup, silently
overriding the env var — a user's ``JAX_PLATFORMS=cpu edl train ...``
would then still initialize (and hang on a wedged) accelerator
transport. Every framework process entry (CLI/master, worker, PS) calls
:func:`honor_jax_platforms_env` before any backend initializes; the
elastic worker additionally re-applies platform selection at each world
formation (parallel/distributed._configure_platform). Unset env leaves
the platform selection untouched.
"""

import os
import sys


def honor_jax_platforms_env():
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax
    except ImportError:
        return  # the caller's import sites will say so
    try:
        jax.config.update("jax_platforms", plat)
    except Exception as e:
        # do NOT swallow silently: the run would proceed on the wrong
        # platform, the exact failure this helper exists to prevent
        print(
            "warning: could not apply JAX_PLATFORMS=%s (%s); the "
            "process may use a different jax platform" % (plat, e),
            file=sys.stderr,
        )
