"""Sharded checkpointing: per-shard array files + a JSON manifest.

The reference checkpoints one protobuf blob written by the master
(reference master/checkpoint_service.py:47-72 + model_utils.py:138-150) —
fine for host-PS models, wrong for device-resident state: a vocab-sharded
embedding table would have to be gathered to one host first. Here each
*process* writes exactly the array shards it holds (deduplicated by
replica id, so replicated leaves are written once, by the process holding
replica 0), and restore materializes arrays directly onto the target
mesh with ``jax.make_array_from_callback`` — every device reads only the
bytes its own shard needs, re-slicing across *different* mesh shapes or
shardings when the world changed between save and restore. This is the
OCDBT/TensorStore layout idea (SURVEY.md §7.1) in the framework's own
dependency-free format.

Directory layout (one directory per version)::

    ckpt_v{N}/
      manifest-{proc}.json   # leaves this process wrote: shape, dtype,
                             #   per-shard global index -> data file
      shard files *.npy      # one per (leaf, distinct shard index)

Multi-host jobs point every process at a shared filesystem (the same
requirement the reference's master checkpoint dir has on k8s volumes).
"""

import glob
import json
import os

import numpy as np

import jax

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.pytree import leaf_entries as _leaf_entries

_MANIFEST_PREFIX = "manifest-"


def _np_save(path, arr):
    """np.save with a round-trippable encoding for non-native dtypes.

    numpy serializes bfloat16 (an ml_dtypes extension type) as raw void
    bytes that np.load cannot cast back; store the bit pattern as uint16
    instead and view it back on read (same shape, itemsize 2).
    """
    arr = np.asarray(arr)
    if arr.dtype.name == "bfloat16":
        np.save(path, arr.view(np.uint16))
    elif arr.dtype.kind == "V":
        raise TypeError(
            "cannot checkpoint dtype %s (no stable numpy encoding)"
            % arr.dtype
        )
    else:
        np.save(path, arr)


def _np_load(path, dtype_name):
    arr = np.load(path)
    if dtype_name == "bfloat16":
        import ml_dtypes

        return arr.view(ml_dtypes.bfloat16)
    return arr


def _np_dtype(name):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _index_to_slices(index, shape):
    """Normalized [(start, stop), ...] for a shard's global index."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return out


def _snapshot_entries(tree, copy_host=False):
    """Lazily yield ``(path, shape, dtype, shards, full)`` save records,
    materializing one leaf's host bytes at a time (so the streaming sync
    writer's peak host memory stays ~one leaf)."""
    for path, leaf in _leaf_entries(tree):
        if not hasattr(leaf, "addressable_shards"):
            # copy_host: a host ndarray leaf must be COPIED when the
            # write happens later/off-thread, or in-place mutation
            # during the background write tears the snapshot
            arr = np.array(leaf) if copy_host else np.asarray(leaf)
            yield (path, arr.shape, arr.dtype, None, arr)
            continue
        shards = [
            (_index_to_slices(s.index, leaf.shape), i, np.asarray(s.data))
            for i, s in enumerate(leaf.addressable_shards)
            if s.replica_id == 0
        ]
        yield (path, tuple(leaf.shape), leaf.dtype, shards, None)


def snapshot_tree(tree):
    """Phase 1 of an async save: capture this process's shard bytes on
    host.

    Enqueues every device->host copy first (``copy_to_host_async``) so
    the transfers overlap each other, then materializes numpy views. The
    result is self-contained host data: the caller may immediately feed
    the original arrays back into a donating ``jit`` (training/step.py
    donates the whole TrainState) while phase 2 —
    :func:`write_snapshot`, which does only disk IO — runs on a
    background thread (see async_checkpoint.AsyncCheckpointer).
    """
    for _, leaf in _leaf_entries(tree):
        if hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if shard.replica_id == 0 and hasattr(
                    shard.data, "copy_to_host_async"
                ):
                    shard.data.copy_to_host_async()
    return list(_snapshot_entries(tree, copy_host=True))


def write_snapshot(
    directory, snap, version=0, process_index=None, logical_dim0=None
):
    """Phase 2 of a save: write save records' shard files + manifest.

    ``snap`` is any iterable of :func:`_snapshot_entries` records — a
    materialized list (async path: pure file IO, safe on a background
    thread) or a lazy generator (sync path: each leaf's device->host
    bytes are pulled, written, and dropped one at a time).
    ``process_index`` is captured by the caller (jax.process_index is
    not thread-safe to first-call off-thread)."""
    os.makedirs(directory, exist_ok=True)
    pid = (
        jax.process_index() if process_index is None else process_index
    )
    # clear THIS process's stale files from a previous write into the
    # same directory (shard counts can change across membership epochs;
    # leftover .p{pid}.s{i} files beyond the new count would merge into
    # restores). Other ranks' files are never touched — they may be
    # writing concurrently. Version-numbering continuity
    # (parallel/elastic.py floors) keeps departed ranks' files out.
    for stale in glob.glob(
        os.path.join(directory, "*.p%d.s*.npy" % pid)
    ) + glob.glob(
        os.path.join(directory, "%s%d.json" % (_MANIFEST_PREFIX, pid))
    ):
        try:
            os.remove(stale)
        except OSError:
            pass
    manifest = {"version": int(version), "leaves": {}}
    for path, shape, dtype, shards, full in snap:
        safe = path.replace("/", ".")
        if shards is None:
            # host array (numpy): process 0 owns it
            if pid == 0:
                fname = "%s.full.npy" % safe
                _np_save(os.path.join(directory, fname), full)
                manifest["leaves"][path] = {
                    "shape": list(shape),
                    "dtype": str(dtype),
                    "shards": [
                        {
                            "slices": _index_to_slices(
                                (slice(None),) * len(shape), shape
                            ),
                            "file": fname,
                        }
                    ],
                }
            continue
        entry = {
            "shape": list(shape),
            "dtype": str(dtype),
            "shards": [],
        }
        if logical_dim0 and path in logical_dim0:
            # the saved dim 0 carries this world's padding; host
            # consumers clip back to the model's declared rows
            entry["logical_dim0"] = int(logical_dim0[path])
        for slices, i, data in shards:
            fname = "%s.p%d.s%d.npy" % (safe, pid, i)
            _np_save(os.path.join(directory, fname), data)
            entry["shards"].append({"slices": slices, "file": fname})
        if entry["shards"]:
            manifest["leaves"][path] = entry
    # manifest written last and renamed into place: a crash mid-save
    # leaves shard files but no manifest, and such directories are
    # ignored by versions()/latest_dir()
    manifest_path = os.path.join(
        directory, "%s%d.json" % (_MANIFEST_PREFIX, pid)
    )
    tmp_path = manifest_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp_path, manifest_path)
    logger.info(
        "sharded checkpoint: process %d wrote %d leaves to %s",
        pid,
        len(manifest["leaves"]),
        directory,
    )


def save_sharded(directory, tree, version=0, logical_dim0=None):
    """Write this process's shards of ``tree`` (a pytree of jax/np
    arrays) into ``directory``. Every participating process must call it
    (collective-free: pure local writes). Streams leaf-by-leaf: peak
    host memory is ~one leaf, not the whole local model.
    ``logical_dim0``: see :func:`write_snapshot`."""
    write_snapshot(
        directory,
        _snapshot_entries(tree),
        version=version,
        logical_dim0=logical_dim0,
    )


def _merged_manifest(directory):
    version, leaves = 0, {}
    paths = sorted(
        glob.glob(os.path.join(directory, _MANIFEST_PREFIX + "*.json"))
    )
    if not paths:
        raise FileNotFoundError(
            "no checkpoint manifests in %s" % directory
        )
    for p in paths:
        with open(p) as f:
            m = json.load(f)
        version = max(version, m["version"])
        for leaf_path, entry in m["leaves"].items():
            merged = leaves.setdefault(
                leaf_path,
                {
                    "shape": entry["shape"],
                    "dtype": entry["dtype"],
                    "shards": [],
                },
            )
            if "logical_dim0" in entry:
                merged["logical_dim0"] = entry["logical_dim0"]
            merged["shards"].extend(entry["shards"])
    return version, leaves


class _LeafReader:
    """Assembles any requested global slice from a leaf's shard files."""

    def __init__(self, directory, entry):
        self._dir = directory
        self._entry = entry
        self._cache = {}

    def _shard_array(self, fname):
        if fname not in self._cache:
            self._cache[fname] = _np_load(
                os.path.join(self._dir, fname), self._entry["dtype"]
            )
        return self._cache[fname]

    def read(self, index, target_shape=None):
        """Assemble the requested slice. ``target_shape`` (when it
        differs from the stored shape) means the caller restores into a
        different world's PADDED space: rows beyond the stored extent
        are padding by construction and fill with zeros — coverage is
        only demanded for the stored rows."""
        shape = self._entry["shape"]
        want = _index_to_slices(index, target_shape or shape)
        out = np.zeros(
            [stop - start for start, stop in want],
            dtype=_np_dtype(self._entry["dtype"]),
        )
        covered = 0
        for shard in self._entry["shards"]:
            have = [tuple(s) for s in shard["slices"]]
            inter = [
                (max(ws, hs), min(we, he))
                for (ws, we), (hs, he) in zip(want, have)
            ]
            if any(s >= e for s, e in inter):
                continue
            src = self._shard_array(shard["file"])
            src_sl = tuple(
                slice(s - hs, e - hs)
                for (s, e), (hs, _) in zip(inter, have)
            )
            dst_sl = tuple(
                slice(s - ws, e - ws)
                for (s, e), (ws, _) in zip(inter, want)
            )
            out[dst_sl] = src[src_sl]
            covered += int(
                np.prod([e - s for s, e in inter], dtype=np.int64)
            )
        # demand coverage only within the STORED extent: the want
        # clamped per-dim to the stored shape
        stored_want = [
            (ws, min(we, int(sd)))
            for (ws, we), sd in zip(want, shape)
        ]
        total = int(
            np.prod(
                [max(0, e - s) for s, e in stored_want], dtype=np.int64
            )
        )
        if covered < total:
            raise ValueError(
                "checkpoint shards cover %d/%d elements of the requested "
                "slice (missing process manifests?)" % (covered, total)
            )
        return out


def load_sharded(directory, shardings, target_shapes=None):
    """Restore a pytree onto device: ``shardings`` is a pytree (same
    structure as saved) of ``jax.sharding.Sharding``; each device
    materializes only its own slice bytes. Returns (version, tree).

    ``target_shapes`` ({'a/b/c': shape}): restore those leaves into a
    DIFFERENT global shape than stored — the new world's padded dim 0
    for PadDim0 leaves (parallel/elastic.py). Rows beyond the stored
    extent fill with zeros; stored rows beyond the target are dropped
    (both are past the logical rows by construction)."""
    version, leaves = _merged_manifest(directory)
    flat_shardings = _leaf_entries(shardings)
    target_shapes = target_shapes or {}
    out_flat = []
    for path, sharding in flat_shardings:
        if path not in leaves:
            raise KeyError(
                "leaf %s not present in checkpoint %s" % (path, directory)
            )
        entry = leaves[path]
        reader = _LeafReader(directory, entry)
        shape = tuple(target_shapes.get(path) or entry["shape"])
        arr = jax.make_array_from_callback(
            shape,
            sharding,
            lambda index, r=reader, t=shape: r.read(
                index, target_shape=t
            ),
        )
        out_flat.append(arr)
    treedef = jax.tree_util.tree_structure(shardings)
    return version, jax.tree_util.tree_unflatten(treedef, out_flat)


def load_sharded_to_host(directory):
    """Restore to host numpy (tooling / model export); full arrays.
    PadDim0 leaves come back clipped to their LOGICAL rows (the
    manifest records ``logical_dim0``), so host consumers — export,
    host-twin scoring — see the model's declared shapes, not a
    world's padding."""
    version, leaves = _merged_manifest(directory)
    tree = {}
    for path, entry in leaves.items():
        reader = _LeafReader(directory, entry)
        full = reader.read(
            tuple(slice(0, d) for d in entry["shape"])
        )
        logical = entry.get("logical_dim0")
        if logical is not None and full.shape[0] > int(logical):
            full = full[: int(logical)]
        node = tree
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = full
    return version, tree


class ShardedCheckpointManager:
    """Ring-retention directory manager (the CheckpointService semantics
    — every checkpoint_steps versions, keep_max directories — for the
    device-resident checkpoint format).

    With ``async_io=True`` saves block only for the device->host
    snapshot; file writes and ring eviction run on a background thread
    (see async_checkpoint.AsyncCheckpointer). Call :meth:`wait` before
    restoring or tearing down."""

    def __init__(
        self, base_dir, checkpoint_steps=0, keep_max=0, async_io=False
    ):
        self._base = base_dir
        self._steps = checkpoint_steps
        self._keep_max = keep_max
        self._expected_writers = None
        self._logical_dim0 = None
        self._async = None
        if async_io:
            from elasticdl_tpu.common.async_checkpoint import (
                AsyncCheckpointer,
            )

            self._async = AsyncCheckpointer()

    def set_expected_writers(self, n):
        """Number of processes writing each version (world size for
        sharded jobs, 1 for replicated rank-0-writes jobs). Lets ring
        eviction distinguish a complete newer version from a torn one;
        the elastic worker refreshes it at every (re-)establish."""
        self._expected_writers = max(1, int(n)) if n else None

    def set_logical_dim0(self, by_path):
        """{'a/b/c': true rows} for PadDim0 leaves the current world
        padded — recorded in manifests so host-side restores clip the
        padding off. Refreshed at every (re-)establish (padding is a
        per-world property)."""
        self._logical_dim0 = dict(by_path) if by_path else None

    @property
    def steps(self):
        return self._steps

    def is_enabled(self):
        return bool(self._steps)

    def need_to_checkpoint(self, version):
        return self.is_enabled() and version % self._steps == 0

    def _dir_for(self, version):
        return os.path.join(self._base, "ckpt_v%d" % version)

    def _manifest_count(self, directory):
        return len(
            glob.glob(os.path.join(directory, _MANIFEST_PREFIX + "*.json"))
        )

    def _evict(self, expected_writers):
        """Ring retention (process 0 only), restorability-gated.

        ``expected_writers`` is passed by the caller rather than read
        off ``self`` because the async-io path runs this on the
        checkpoint writer thread: an elastic resize between submit and
        write would otherwise have the in-flight eviction judge OLD
        versions' manifests against the NEW world's writer count and
        possibly delete the last restorable state (edlint R8 caught the
        unlocked cross-thread read; the value now travels with the
        snapshot it describes).

        A version is only evicted once some NEWER version is at least as
        complete — otherwise rank 0 could delete the last fully-written
        checkpoint while a straggler rank is still filling the newest
        one, and a kill in that window would leave nothing restorable.
        "Complete" is ``expected_writers`` manifests when the worker told
        us the world size (set_expected_writers — both worker planes
        call it at every (re-)establish, making it the authoritative
        bar), else — conservatively — the max of ``jax.process_count()``
        and the manifest counts across ALL kept versions. The victim's
        own count would be too weak a bar: after a world GROW, a torn
        newer version can already carry as many manifests as a complete
        small-world victim, and evicting that victim would delete the
        only restorable state. In a multi-process jax world the
        process_count term closes the remaining tie (torn-new count ==
        complete-old count == old world size); the max-across-kept term
        can over-hold after a world SHRINK, but only until the next
        establish refreshes expected_writers — a bounded disk cost, not
        a correctness one."""
        kept = sorted(self.versions())
        while len(kept) > self._keep_max:
            victim_dir = self._dir_for(kept[0])
            counts = {
                v: self._manifest_count(self._dir_for(v)) for v in kept
            }
            if expected_writers:
                # after a world GROW, a newer version is only restorable
                # once every CURRENT rank's manifest landed — the
                # victim's (smaller) count must not lower the bar
                need = expected_writers
            else:
                need = max(jax.process_count(), *counts.values())
            if not any(counts[v] >= need for v in kept[1:]):
                # every newer version is still torn; deleting the victim
                # would risk the last restorable state — hold until a
                # newer one completes (the next save retries)
                break
            kept.pop(0)
            for f in glob.glob(os.path.join(victim_dir, "*")):
                os.remove(f)
            os.rmdir(victim_dir)

    def save(self, tree, version):
        directory = self._dir_for(version)
        pid = jax.process_index()
        # snapshot per-world config at submit time: the async write may
        # land after an elastic resize rewrote these for the NEXT world
        logical = self._logical_dim0
        expected = self._expected_writers
        if self._async is not None:
            snap = snapshot_tree(tree)

            def _write():
                write_snapshot(
                    directory,
                    snap,
                    version=version,
                    process_index=pid,
                    logical_dim0=logical,
                )
                if self._keep_max and pid == 0:
                    self._evict(expected)

            self._async.submit(_write, label="ckpt_v%d" % version)
            return directory
        save_sharded(
            directory, tree, version=version, logical_dim0=logical
        )
        if self._keep_max and pid == 0:
            self._evict(expected)
        return directory

    def wait(self):
        """Drain in-flight async saves (no-op in sync mode)."""
        if self._async is not None:
            self._async.wait()

    def close(self):
        if self._async is not None:
            self._async.close()
            self._async = None

    def versions(self):
        """Versions with at least one complete manifest (a crash mid-save
        leaves a manifest-less directory, which must not wedge resume)."""
        out = []
        for d in glob.glob(os.path.join(self._base, "ckpt_v*")):
            if not glob.glob(os.path.join(d, _MANIFEST_PREFIX + "*.json")):
                continue
            try:
                out.append(int(os.path.basename(d)[len("ckpt_v"):]))
            except ValueError:
                continue
        return sorted(out)

    def latest_dir(self):
        versions = self.versions()
        return self._dir_for(versions[-1]) if versions else None

    def dirs_newest_first(self):
        """Candidate restore directories, newest first. Callers iterate
        and fall through on load errors: a killed rank can leave the
        newest version torn (load raises on incomplete shard coverage)
        while an older complete one sits behind it."""
        return [
            self._dir_for(v) for v in sorted(self.versions(), reverse=True)
        ]
