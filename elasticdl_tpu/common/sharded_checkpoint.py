"""Sharded checkpointing: per-shard array files + a JSON manifest.

The reference checkpoints one protobuf blob written by the master
(reference master/checkpoint_service.py:47-72 + model_utils.py:138-150) —
fine for host-PS models, wrong for device-resident state: a vocab-sharded
embedding table would have to be gathered to one host first. Here each
*process* writes exactly the array shards it holds (deduplicated by
replica id, so replicated leaves are written once, by the process holding
replica 0), and restore materializes arrays directly onto the target
mesh with ``jax.make_array_from_callback`` — every device reads only the
bytes its own shard needs, re-slicing across *different* mesh shapes or
shardings when the world changed between save and restore. This is the
OCDBT/TensorStore layout idea (SURVEY.md §7.1) in the framework's own
dependency-free format.

Directory layout (one directory per version)::

    ckpt_v{N}/
      manifest-{proc}.json   # leaves this process wrote: shape, dtype,
                             #   per-shard global index -> data file
      shard files *.npy      # one per (leaf, distinct shard index)

Multi-host jobs point every process at a shared filesystem (the same
requirement the reference's master checkpoint dir has on k8s volumes).
"""

import glob
import json
import os

import numpy as np

import jax

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.pytree import leaf_entries as _leaf_entries

_MANIFEST_PREFIX = "manifest-"


def _np_save(path, arr):
    """np.save with a round-trippable encoding for non-native dtypes.

    numpy serializes bfloat16 (an ml_dtypes extension type) as raw void
    bytes that np.load cannot cast back; store the bit pattern as uint16
    instead and view it back on read (same shape, itemsize 2).
    """
    arr = np.asarray(arr)
    if arr.dtype.name == "bfloat16":
        np.save(path, arr.view(np.uint16))
    elif arr.dtype.kind == "V":
        raise TypeError(
            "cannot checkpoint dtype %s (no stable numpy encoding)"
            % arr.dtype
        )
    else:
        np.save(path, arr)


def _np_load(path, dtype_name):
    arr = np.load(path)
    if dtype_name == "bfloat16":
        import ml_dtypes

        return arr.view(ml_dtypes.bfloat16)
    return arr


def _np_dtype(name):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _index_to_slices(index, shape):
    """Normalized [(start, stop), ...] for a shard's global index."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return out


def save_sharded(directory, tree, version=0):
    """Write this process's shards of ``tree`` (a pytree of jax/np
    arrays) into ``directory``. Every participating process must call it
    (collective-free: pure local writes)."""
    os.makedirs(directory, exist_ok=True)
    pid = jax.process_index()
    manifest = {"version": int(version), "leaves": {}}
    for path, leaf in _leaf_entries(tree):
        safe = path.replace("/", ".")
        if not hasattr(leaf, "addressable_shards"):
            # host array (numpy): process 0 owns it
            if pid == 0:
                fname = "%s.full.npy" % safe
                _np_save(os.path.join(directory, fname), np.asarray(leaf))
                manifest["leaves"][path] = {
                    "shape": list(np.shape(leaf)),
                    "dtype": str(np.asarray(leaf).dtype),
                    "shards": [
                        {
                            "slices": _index_to_slices(
                                (slice(None),) * np.ndim(leaf),
                                np.shape(leaf),
                            ),
                            "file": fname,
                        }
                    ],
                }
            continue
        entry = {
            "shape": list(leaf.shape),
            "dtype": str(leaf.dtype),
            "shards": [],
        }
        for i, shard in enumerate(leaf.addressable_shards):
            if shard.replica_id != 0:
                continue  # replicated copy: someone else's replica 0 writes
            fname = "%s.p%d.s%d.npy" % (safe, pid, i)
            _np_save(
                os.path.join(directory, fname), np.asarray(shard.data)
            )
            entry["shards"].append(
                {
                    "slices": _index_to_slices(shard.index, leaf.shape),
                    "file": fname,
                }
            )
        if entry["shards"]:
            manifest["leaves"][path] = entry
    # manifest written last and renamed into place: a crash mid-save
    # leaves shard files but no manifest, and such directories are
    # ignored by versions()/latest_dir()
    manifest_path = os.path.join(
        directory, "%s%d.json" % (_MANIFEST_PREFIX, pid)
    )
    tmp_path = manifest_path + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp_path, manifest_path)
    logger.info(
        "sharded checkpoint: process %d wrote %d leaves to %s",
        pid,
        len(manifest["leaves"]),
        directory,
    )


def _merged_manifest(directory):
    version, leaves = 0, {}
    paths = sorted(
        glob.glob(os.path.join(directory, _MANIFEST_PREFIX + "*.json"))
    )
    if not paths:
        raise FileNotFoundError(
            "no checkpoint manifests in %s" % directory
        )
    for p in paths:
        with open(p) as f:
            m = json.load(f)
        version = max(version, m["version"])
        for leaf_path, entry in m["leaves"].items():
            merged = leaves.setdefault(
                leaf_path,
                {
                    "shape": entry["shape"],
                    "dtype": entry["dtype"],
                    "shards": [],
                },
            )
            merged["shards"].extend(entry["shards"])
    return version, leaves


class _LeafReader:
    """Assembles any requested global slice from a leaf's shard files."""

    def __init__(self, directory, entry):
        self._dir = directory
        self._entry = entry
        self._cache = {}

    def _shard_array(self, fname):
        if fname not in self._cache:
            self._cache[fname] = _np_load(
                os.path.join(self._dir, fname), self._entry["dtype"]
            )
        return self._cache[fname]

    def read(self, index):
        shape = self._entry["shape"]
        want = _index_to_slices(index, shape)
        out = np.zeros(
            [stop - start for start, stop in want],
            dtype=_np_dtype(self._entry["dtype"]),
        )
        covered = 0
        for shard in self._entry["shards"]:
            have = [tuple(s) for s in shard["slices"]]
            inter = [
                (max(ws, hs), min(we, he))
                for (ws, we), (hs, he) in zip(want, have)
            ]
            if any(s >= e for s, e in inter):
                continue
            src = self._shard_array(shard["file"])
            src_sl = tuple(
                slice(s - hs, e - hs)
                for (s, e), (hs, _) in zip(inter, have)
            )
            dst_sl = tuple(
                slice(s - ws, e - ws)
                for (s, e), (ws, _) in zip(inter, want)
            )
            out[dst_sl] = src[src_sl]
            covered += int(
                np.prod([e - s for s, e in inter], dtype=np.int64)
            )
        total = int(np.prod(out.shape, dtype=np.int64))
        if covered < total:
            raise ValueError(
                "checkpoint shards cover %d/%d elements of the requested "
                "slice (missing process manifests?)" % (covered, total)
            )
        return out


def load_sharded(directory, shardings):
    """Restore a pytree onto device: ``shardings`` is a pytree (same
    structure as saved) of ``jax.sharding.Sharding``; each device
    materializes only its own slice bytes. Returns (version, tree)."""
    version, leaves = _merged_manifest(directory)
    flat_shardings = _leaf_entries(shardings)
    out_flat = []
    for path, sharding in flat_shardings:
        if path not in leaves:
            raise KeyError(
                "leaf %s not present in checkpoint %s" % (path, directory)
            )
        entry = leaves[path]
        reader = _LeafReader(directory, entry)
        arr = jax.make_array_from_callback(
            tuple(entry["shape"]),
            sharding,
            lambda index, r=reader: r.read(index),
        )
        out_flat.append(arr)
    treedef = jax.tree_util.tree_structure(shardings)
    return version, jax.tree_util.tree_unflatten(treedef, out_flat)


def load_sharded_to_host(directory):
    """Restore to host numpy (tooling / model export); full arrays."""
    version, leaves = _merged_manifest(directory)
    tree = {}
    for path, entry in leaves.items():
        reader = _LeafReader(directory, entry)
        full = reader.read(
            tuple(slice(0, d) for d in entry["shape"])
        )
        node = tree
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = full
    return version, tree


class ShardedCheckpointManager:
    """Ring-retention directory manager (the CheckpointService semantics
    — every checkpoint_steps versions, keep_max directories — for the
    device-resident checkpoint format)."""

    def __init__(self, base_dir, checkpoint_steps=0, keep_max=0):
        self._base = base_dir
        self._steps = checkpoint_steps
        self._keep_max = keep_max

    @property
    def steps(self):
        return self._steps

    def is_enabled(self):
        return bool(self._steps)

    def need_to_checkpoint(self, version):
        return self.is_enabled() and version % self._steps == 0

    def _dir_for(self, version):
        return os.path.join(self._base, "ckpt_v%d" % version)

    def save(self, tree, version):
        directory = self._dir_for(version)
        save_sharded(directory, tree, version)
        if self._keep_max and jax.process_index() == 0:
            kept = sorted(self.versions())
            while len(kept) > self._keep_max:
                victim = self._dir_for(kept.pop(0))
                for f in glob.glob(os.path.join(victim, "*")):
                    os.remove(f)
                os.rmdir(victim)
        return directory

    def versions(self):
        """Versions with at least one complete manifest (a crash mid-save
        leaves a manifest-less directory, which must not wedge resume)."""
        out = []
        for d in glob.glob(os.path.join(self._base, "ckpt_v*")):
            if not glob.glob(os.path.join(d, _MANIFEST_PREFIX + "*.json")):
                continue
            try:
                out.append(int(os.path.basename(d)[len("ckpt_v"):]))
            except ValueError:
                continue
        return sorted(out)

    def latest_dir(self):
        versions = self.versions()
        return self._dir_for(versions[-1]) if versions else None
