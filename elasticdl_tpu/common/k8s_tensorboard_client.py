"""TensorBoard service exposure on Kubernetes.

Parity: reference common/k8s_tensorboard_client.py:20-53 — creates a
LoadBalancer Service targeting the master pod's TensorBoard port and polls
for its external ingress IP.
"""

import time

from elasticdl_tpu.common.k8s_client import (
    ELASTICDL_JOB_KEY,
    ELASTICDL_REPLICA_INDEX_KEY,
    ELASTICDL_REPLICA_TYPE_KEY,
    Client,
    _require_k8s,
)
from elasticdl_tpu.common.log_utils import default_logger as logger


class TensorBoardClient:
    def __init__(self, **kwargs):
        self._k8s_client = Client(**kwargs)

    def _get_tensorboard_service_name(self):
        return "tensorboard-" + self._k8s_client.job_name

    def create_tensorboard_service(
        self, port=80, target_port=6006, service_type="LoadBalancer"
    ):
        k8s_client, _, _ = _require_k8s()
        service = k8s_client.V1Service(
            metadata=k8s_client.V1ObjectMeta(
                name=self._get_tensorboard_service_name(),
                labels={
                    "app": "elasticdl",
                    ELASTICDL_JOB_KEY: self._k8s_client.job_name,
                },
                owner_references=Client.create_owner_reference(
                    self._k8s_client.get_master_pod()
                ),
                namespace=self._k8s_client.namespace,
            ),
            spec=k8s_client.V1ServiceSpec(
                ports=[
                    k8s_client.V1ServicePort(
                        port=port, target_port=target_port
                    )
                ],
                selector={
                    ELASTICDL_JOB_KEY: self._k8s_client.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: "master",
                    ELASTICDL_REPLICA_INDEX_KEY: "0",
                },
                type=service_type,
            ),
        )
        return self._k8s_client.client.create_namespaced_service(
            self._k8s_client.namespace, service
        )

    def _get_tensorboard_service(self):
        k8s_client, _, _ = _require_k8s()
        try:
            return self._k8s_client.client.read_namespaced_service(
                name=self._get_tensorboard_service_name(),
                namespace=self._k8s_client.namespace,
            )
        except k8s_client.api_client.ApiException as e:
            logger.warning(
                "Exception when reading TensorBoard service: %s" % e
            )
            return None

    def get_tensorboard_external_ip(self, check_interval=5, wait_secs=120):
        for _ in range(wait_secs // check_interval):
            service = self._get_tensorboard_service()
            if (
                service
                and service.status.load_balancer.ingress
                and service.status.load_balancer.ingress[0].ip
            ):
                return service.status.load_balancer.ingress[0].ip
            time.sleep(check_interval)
        return None
