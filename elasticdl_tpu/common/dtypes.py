"""numpy <-> wire dtype maps.

Parity: reference common/dtypes.py:23-43 + proto enum tensor_dtype.proto:6-18.
Extended with bfloat16 (first-class on TPU) via ml_dtypes.
"""

import numpy as np

try:  # bfloat16 numpy dtype ships with jax
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover
    _BF16 = None

# wire name -> numpy dtype
_NAME_TO_NP = {
    "int8": np.dtype(np.int8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "uint8": np.dtype(np.uint8),
    "uint16": np.dtype(np.uint16),
    "uint32": np.dtype(np.uint32),
    "uint64": np.dtype(np.uint64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    "bool": np.dtype(np.bool_),
}
if _BF16 is not None:
    _NAME_TO_NP["bfloat16"] = _BF16

_NP_TO_NAME = {v: k for k, v in _NAME_TO_NP.items()}


def dtype_numpy_to_name(dtype):
    """Wire name for a numpy dtype; raises on unsupported dtypes."""
    dtype = np.dtype(dtype) if not isinstance(dtype, np.dtype) else dtype
    if dtype not in _NP_TO_NAME:
        raise ValueError("Unsupported tensor dtype: %s" % dtype)
    return _NP_TO_NAME[dtype]


def dtype_name_to_numpy(name):
    if name not in _NAME_TO_NP:
        raise ValueError("Unsupported wire dtype name: %s" % name)
    return _NAME_TO_NP[name]


def is_numpy_dtype_allowed(dtype):
    try:
        dtype_numpy_to_name(dtype)
        return True
    except ValueError:
        return False
