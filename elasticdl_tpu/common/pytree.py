"""Small pytree helpers shared across the framework."""

import jax


def key_path_names(key_path):
    """Normalize a jax tree key path to a tuple of name strings.

    Handles DictKey (.key), GetAttrKey (.name), and SequenceKey (.idx) —
    the one place the tree-path naming convention lives (used by both
    sharded checkpoints and the trainer's param-sharding placement, so
    save paths and placement paths can never drift apart).
    """
    names = []
    for k in key_path:
        name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "name", None)
        if name is None:
            name = getattr(k, "idx", None)
        names.append(str(name))
    return tuple(names)


def leaf_entries(tree):
    """[(path-string, leaf)] with '/'-joined tree paths."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        ("/".join(key_path_names(key_path)), leaf)
        for key_path, leaf in flat
    ]
