"""Resource-string parser.

Parity: reference common/k8s_resource.py:38-80 — parse
``"cpu=1,memory=4096Mi,tpu=8"`` into a dict with validation. The TPU
resource name maps to the google.com/tpu extended resource at pod-spec
render time (k8s_client.py).
"""

_ALLOWED = {
    "cpu",
    "memory",
    "disk",
    "gpu",
    "tpu",
    "ephemeral-storage",
    "ephemeral_storage",
}


def parse_resource(resource_str):
    """Resource string -> dict; validates names and formats."""
    kvs = {}
    if not resource_str:
        return kvs
    for pair in resource_str.split(","):
        key, _, value = pair.partition("=")
        key = key.strip()
        value = value.strip()
        if not key or not value:
            raise ValueError(
                "invalid resource spec %r in %r" % (pair, resource_str)
            )
        base = key.split("/")[-1]
        if base not in _ALLOWED and "/" not in key:
            raise ValueError(
                "resource name %r must be one of %s or a fully-qualified "
                "extended resource" % (key, sorted(_ALLOWED))
            )
        if base == "cpu":
            # cpu may be fractional or milli-cpu
            v = value[:-1] if value.endswith("m") else value
            float(v)  # raises if malformed
        elif base == "memory" or base.startswith("ephemeral"):
            if not any(
                value.endswith(suffix)
                for suffix in ("Ki", "Mi", "Gi", "Ti", "K", "M", "G", "T")
            ) and not value.isdigit():
                raise ValueError("invalid quantity %r for %s" % (value, key))
        elif base in ("gpu", "tpu"):
            int(value)
        if key in kvs:
            raise ValueError("duplicate resource name %r" % key)
        kvs[key] = value
    return kvs
