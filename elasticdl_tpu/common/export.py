"""Standard serving/export artifact: versioned, externally loadable.

Parity: the reference's SAVE_MODEL task exports a tf SavedModel any
serving stack can load (reference worker/worker.py:695-715,
common/model_handler.py:108-141). The TPU-native equivalent is a
directory artifact built from the two JAX-ecosystem standards:

- ``params/`` — an **Orbax** checkpoint of the parameter pytree
  (``orbax.checkpoint.StandardCheckpointer``), loadable by any JAX
  stack without this framework.
- ``serving_fn.jaxexport`` — optional: the model's inference forward
  serialized with **jax.export** (StableHLO), batch-polymorphic and
  multi-platform (cpu+tpu), so a fresh process can serve without the
  model-zoo source at all: ``deserialize(blob).call(params, features)``.
- ``model.chkpt`` — the framework's own tensor-frame codec (the file
  ``--checkpoint_filename_for_init`` already accepts), kept so older
  loaders keep working.
- ``MANIFEST.json`` — format version, model version, leaf spec (name,
  shape, dtype), provenance metadata (model_def/model_params), and the
  artifact listing. The manifest is the stability contract: loaders
  should dispatch on ``format``/``format_version``.

Layout is documented in docs/export.md; :func:`load_export` is the
reference loader and the fresh-process round trip is locked by
tests/test_export.py.
"""

import json
import os
import time
from dataclasses import dataclass

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger as logger

EXPORT_FORMAT = "elasticdl-tpu-export"
EXPORT_FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
_PARAMS_DIR = "params"
_SERVING_FILE = "serving_fn.jaxexport"
_LEGACY_CHKPT = "model.chkpt"


def is_export_dir(path):
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f).get("format") == EXPORT_FORMAT
    except (OSError, ValueError):
        return False


def _leaf_spec(params):
    import jax

    spec = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        spec[name] = {
            "shape": list(np.shape(leaf)),
            "dtype": str(np.asarray(leaf).dtype),
        }
    return spec


def _write_orbax_params(params_path, params, legacy_path):
    """Write the orbax params artifact; returns False on failure.

    In a multi-process jax world (the elastic plane), orbax's save runs
    a GLOBAL process barrier (sync_global_processes) — but only the
    export-task rank is exporting, so an in-process save deadlocks the
    job against peers still in their training collectives. There the
    save runs in a fresh single-process subprocess fed by the
    already-written legacy member (same arrays, nested by the "/" path
    convention of pytree_to_named_arrays)."""
    import jax

    if jax.process_count() <= 1:
        import orbax.checkpoint as ocp

        # orbax refuses to overwrite; an export dir is written once per
        # timestamped path but a retried SAVE_MODEL task may reuse one
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(params_path, params, force=True)
        ckptr.wait_until_finished()
        return True

    import subprocess
    import sys

    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import sys\n"
        "import orbax.checkpoint as ocp\n"
        "from elasticdl_tpu.common.model_utils import "
        "load_from_checkpoint_file\n"
        "from elasticdl_tpu.common.tensor import "
        "named_arrays_to_nested\n"
        "_, named = load_from_checkpoint_file(sys.argv[1])\n"
        "tree = named_arrays_to_nested(named)\n"
        "ckptr = ocp.StandardCheckpointer()\n"
        "ckptr.save(sys.argv[2], tree, force=True)\n"
        "ckptr.wait_until_finished()\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # a child must not inherit the parent's distributed-world wiring
    for k in ("EDL_DIST_PLATFORM", "EDL_LOCAL_DEVICES"):
        env.pop(k, None)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env.get("PYTHONPATH", "")
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code, legacy_path, params_path],
            capture_output=True,
            text=True,
            timeout=600,
            env=env,
        )
    except Exception as e:  # noqa: BLE001 - optional artifact member
        logger.warning("orbax params subprocess failed: %s", e)
        return False
    if proc.returncode != 0:
        logger.warning(
            "orbax params subprocess failed (rc=%d): %s",
            proc.returncode,
            (proc.stderr or "")[-2000:],
        )
        return False
    return True


def _export_serving_fn(path, serving_fn, params, example_features):
    """Serialize ``serving_fn(params, features)`` with a symbolic batch
    dimension for cpu+tpu. Best-effort: a model whose forward cannot be
    lowered for both platforms (e.g. a TPU-only Pallas kernel in the
    auto-attention path) still exports params + manifest, it just ships
    without the source-free serving plane; the manifest records which."""
    import jax
    from jax import export as jexport

    try:
        (batch,) = jexport.symbolic_shape("batch")

        def feature_spec(leaf):
            arr = np.asarray(leaf)
            return jax.ShapeDtypeStruct(
                (batch,) + arr.shape[1:], arr.dtype
            )

        features_spec = jax.tree_util.tree_map(
            feature_spec, example_features
        )
        params_spec = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype
            ),
            params,
        )
        exported = jexport.export(
            jax.jit(serving_fn), platforms=("cpu", "tpu")
        )(params_spec, features_spec)
        blob = exported.serialize()
    except Exception as e:  # noqa: BLE001 - optional plane, reported
        logger.warning(
            "serving-fn export skipped (params-only artifact): %s", e
        )
        return False
    with open(path, "wb") as f:
        f.write(blob)
    return True


def export_model(
    export_dir,
    params,
    version,
    metadata=None,
    serving_fn=None,
    example_features=None,
    extra_named=None,
):
    """Write the full artifact; returns the manifest dict.

    ``params`` is the model parameter pytree (host or device arrays).
    ``serving_fn(params, features) -> outputs`` plus one
    ``example_features`` batch enables the source-free serving plane.
    ``extra_named``: additional {name: array} entries merged into the
    LEGACY checkpoint only (the master-KV embedding-table export: the
    prefixed keys round-trip through ``checkpoint_filename_for_init``,
    which re-imports them into the embedding store; they are not model
    pytree leaves, so the orbax/serving artifacts don't carry them).
    """
    import jax

    from elasticdl_tpu.common.model_utils import save_checkpoint_to_file
    from elasticdl_tpu.common.tensor import pytree_to_named_arrays

    export_dir = os.path.abspath(export_dir)
    os.makedirs(export_dir, exist_ok=True)
    params = jax.tree_util.tree_map(np.asarray, params)

    legacy_named = pytree_to_named_arrays(params)
    if extra_named:
        legacy_named = dict(legacy_named)
        legacy_named.update(
            {name: np.asarray(arr) for name, arr in extra_named.items()}
        )
    legacy_path = os.path.join(export_dir, _LEGACY_CHKPT)
    save_checkpoint_to_file(legacy_named, version, legacy_path)

    params_path = os.path.join(export_dir, _PARAMS_DIR)
    has_params = _write_orbax_params(params_path, params, legacy_path)

    has_serving = False
    if serving_fn is not None and example_features is not None:
        has_serving = _export_serving_fn(
            os.path.join(export_dir, _SERVING_FILE),
            serving_fn,
            params,
            example_features,
        )

    manifest = {
        "format": EXPORT_FORMAT,
        "format_version": EXPORT_FORMAT_VERSION,
        "model_version": int(version),
        "created_unix": int(time.time()),
        "jax_version": jax.__version__,
        "metadata": dict(metadata or {}),
        "extra_named": sorted(extra_named) if extra_named else [],
        "leaves": _leaf_spec(params),
        "artifacts": {
            "params": _PARAMS_DIR if has_params else None,
            "legacy_checkpoint": _LEGACY_CHKPT,
            "serving_fn": _SERVING_FILE if has_serving else None,
        },
    }
    tmp = os.path.join(export_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    # manifest last + atomic: its presence marks a complete artifact
    os.replace(tmp, os.path.join(export_dir, MANIFEST_NAME))
    logger.info(
        "exported model v%d to %s (serving_fn=%s)",
        version,
        export_dir,
        has_serving,
    )
    return manifest


def export_provenance(model_zoo, model_def, model_params):
    """The manifest metadata every worker records: enough for a serving
    process to rebuild the model without guessing flags."""
    return {
        "model_zoo": model_zoo,
        "model_def": model_def,
        "model_params": model_params or "",
    }


def example_batch_for_export(
    dataset, dataset_fn, metadata, minibatch_size, mode
):
    """One prediction-mode batch from the SAVE_MODEL task's dataset: the
    signature source for the serialized serving function (the reference
    traces its SavedModel signature the same way, reference
    worker/worker.py:695-715). None (params-only artifact) when the
    shard is empty or the pipeline errors."""
    if not dataset:
        return None
    try:
        ds = dataset_fn(dataset, mode, metadata)
        for features in ds.batch(max(1, minibatch_size)):
            return features
    except Exception as e:  # noqa: BLE001 - optional plane
        logger.warning("no example batch for serving export: %s", e)
    return None


def make_serving_fn(model, state):
    """Inference forward ``(params, features) -> output`` for export.

    Mutable collections (e.g. batch-norm stats) are closed over and
    baked into the serialized function as constants — exported models
    carry no mutable state, matching the loader contract in
    worker/elastic_allreduce_worker._load_eval_only_params."""
    from elasticdl_tpu.training.step import apply_model

    def serving_fn(params, features):
        output, _ = apply_model(
            model, params, state, features, training=False
        )
        return output

    return serving_fn


@dataclass
class ExportedModel:
    """A loaded export: ``params`` pytree + manifest; ``serve`` works
    source-free when the artifact carries a serving function."""

    export_dir: str
    manifest: dict
    params: object
    _serving = None

    @property
    def version(self):
        return self.manifest["model_version"]

    @property
    def metadata(self):
        return self.manifest["metadata"]

    def has_serving_fn(self):
        return bool(self.manifest["artifacts"].get("serving_fn"))

    def serve(self, features):
        if not self.has_serving_fn():
            raise RuntimeError(
                "export at %s carries no serving function; rebuild the "
                "model from metadata['model_def'] and apply params"
                % self.export_dir
            )
        if self._serving is None:
            from jax import export as jexport

            with open(
                os.path.join(
                    self.export_dir,
                    self.manifest["artifacts"]["serving_fn"],
                ),
                "rb",
            ) as f:
                self._serving = jexport.deserialize(f.read())
        return self._serving.call(self.params, features)


def load_export(export_dir):
    """Load an export artifact written by :func:`export_model`."""
    export_dir = os.path.abspath(export_dir)
    with open(os.path.join(export_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    if manifest.get("format") != EXPORT_FORMAT:
        raise ValueError(
            "%s is not an %s artifact" % (export_dir, EXPORT_FORMAT)
        )
    if manifest.get("format_version", 0) > EXPORT_FORMAT_VERSION:
        raise ValueError(
            "export format v%s is newer than this loader (v%d)"
            % (manifest.get("format_version"), EXPORT_FORMAT_VERSION)
        )
    if manifest["artifacts"].get("params"):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(
            os.path.join(
                export_dir, manifest["artifacts"]["params"]
            )
        )
    else:
        # params-member-less artifact (orbax write failed at export):
        # the legacy codec carries the same arrays, nested back by the
        # "/" path convention
        from elasticdl_tpu.common.model_utils import (
            load_from_checkpoint_file,
        )

        from elasticdl_tpu.common.tensor import (
            named_arrays_to_nested,
        )

        _, named = load_from_checkpoint_file(export_dir)
        params = named_arrays_to_nested(named)
    return ExportedModel(
        export_dir=export_dir, manifest=manifest, params=params
    )
