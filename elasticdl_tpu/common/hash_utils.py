"""Deterministic partition/placement hashing.

Parity: reference common/hash_utils.py:4-49 — variable->PS-shard placement
by name hash, embedding-row->shard placement by id modulo, and a scatter
helper grouping (values, ids) per shard. The same functions drive the
TPU-native row-sharded embedding layout (shard = mesh slice instead of a PS
pod), so checkpoint/restore row placement is stable across backends.
"""

import hashlib

import numpy as np


def string_to_id(name, bucket_num):
    """Stable shard id for a parameter name (sha256 % buckets)."""
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive")
    digest = hashlib.sha256(name.encode("utf-8")).hexdigest()
    return int(digest, 16) % bucket_num


def int_to_id(number, bucket_num):
    """Shard id for an embedding row id (id % buckets)."""
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive")
    return int(number) % bucket_num


def scatter_embedding_vector(values, ids, bucket_num):
    """Group rows per shard: returns {shard_id: (values_subset, ids_subset)}.

    ``values`` is (n, dim); ``ids`` is (n,). Vectorized (the reference loops
    per element, hash_utils.py:14-49).
    """
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive")
    values = np.asarray(values)
    ids = np.asarray(ids, dtype=np.int64)
    if values.shape[0] != ids.shape[0]:
        raise ValueError("values and ids must have the same leading dim")
    shard_ids = ids % bucket_num
    result = {}
    for shard in np.unique(shard_ids):
        mask = shard_ids == shard
        result[int(shard)] = (values[mask], ids[mask])
    return result
