"""Dependency-free TensorBoard event-file writer.

Parity: the reference's TensorBoard service logs eval metrics through
``tf.summary.create_file_writer`` / ``tf.summary.scalar`` (reference
master/tensorboard_service.py:27-45), producing TFRecord-framed files a
``tensorboard --logdir`` process renders. This module writes the same
on-disk format — ``events.out.tfevents.*`` files of length-prefixed,
CRC32C-masked records carrying hand-serialized ``Event`` protos — with
no TensorFlow (or protoc) dependency, the same stance as rpc/core.py's
self-describing frames.

Format (tensorflow/core/lib/io/record_writer.cc):

    uint64  length          (little-endian)
    uint32  masked_crc32c(length bytes)
    bytes   data            (serialized Event proto)
    uint32  masked_crc32c(data)

where ``masked_crc = ((crc >> 15 | crc << 17) + 0xa282ead8) mod 2^32``
over the Castagnoli CRC-32. The first record of every file is an Event
with ``file_version = "brain.Event:2"``; scalars are Summary.Value
entries with ``simple_value`` set, which every TensorBoard release
renders in the scalar dashboard.
"""

import os
import socket
import struct
import threading
import time

_CRC_TABLE = None


def _crc32c_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78  # Castagnoli, reflected
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data):
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data):
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(n):
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _key(field, wire_type):
    return _varint(field << 3 | wire_type)


def _bytes_field(field, payload):
    return _key(field, 2) + _varint(len(payload)) + payload


def _summary_value(tag, value):
    # Summary.Value{ tag=1 (string), simple_value=2 (float) }
    payload = _bytes_field(1, tag.encode("utf-8"))
    payload += _key(2, 5) + struct.pack("<f", float(value))
    return payload


def encode_scalar_event(wall_time, step, scalars):
    """Event{wall_time=1 (double), step=2 (int64), summary=5} with one
    Summary.Value per (tag, value) pair."""
    event = _key(1, 1) + struct.pack("<d", wall_time)
    event += _key(2, 0) + _varint(int(step) & 0xFFFFFFFFFFFFFFFF)
    summary = b"".join(
        _bytes_field(1, _summary_value(tag, value))
        for tag, value in scalars
    )
    event += _bytes_field(5, summary)
    return event


def encode_file_version_event(wall_time):
    event = _key(1, 1) + struct.pack("<d", wall_time)
    return event + _bytes_field(3, b"brain.Event:2")


def frame_record(data):
    header = struct.pack("<Q", len(data))
    return (
        header
        + struct.pack("<I", masked_crc32c(header))
        + data
        + struct.pack("<I", masked_crc32c(data))
    )


class EventFileWriter:
    """Appends scalar events to one ``events.out.tfevents.*`` file.

    Thread-safe; writes are flushed per call (eval cadence, not the hot
    path — the hot path's metrics ride the deferred-sync step loop)."""

    def __init__(self, logdir, filename_suffix=""):
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(
            logdir,
            "events.out.tfevents.%d.%s%s"
            % (int(time.time()), socket.gethostname(), filename_suffix),
        )
        self._lock = threading.Lock()
        self._f = open(self.path, "ab")
        self._write(encode_file_version_event(time.time()))

    def _write(self, event_bytes):
        self._f.write(frame_record(event_bytes))
        self._f.flush()

    def add_scalars(self, scalars, step, wall_time=None):
        """``scalars``: iterable of (tag, value); one Event per call."""
        scalars = list(scalars)
        if not scalars:
            return
        with self._lock:
            self._write(
                encode_scalar_event(
                    wall_time if wall_time is not None else time.time(),
                    step,
                    scalars,
                )
            )

    def add_scalar(self, tag, value, step, wall_time=None):
        self.add_scalars([(tag, value)], step, wall_time)

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.close()


def read_events(path):
    """Parse an event file back into [(wall_time, step, [(tag, value)])].

    The verification half of the round trip (tests, debugging); tolerates
    a torn final record the way TensorBoard's loader does — stop at the
    first incomplete frame."""
    events = []
    with open(path, "rb") as f:
        blob = f.read()
    off = 0
    while off + 12 <= len(blob):
        (length,) = struct.unpack_from("<Q", blob, off)
        if off + 12 + length + 4 > len(blob):
            break
        header = blob[off : off + 8]
        (len_crc,) = struct.unpack_from("<I", blob, off + 8)
        data = blob[off + 12 : off + 12 + length]
        (data_crc,) = struct.unpack_from("<I", blob, off + 12 + length)
        if (
            masked_crc32c(header) != len_crc
            or masked_crc32c(data) != data_crc
        ):
            raise ValueError("corrupt event record at offset %d" % off)
        events.append(_decode_event(data))
        off += 12 + length + 4
    return events


def _read_varint(buf, off):
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _decode_event(data):
    wall_time, step, scalars = 0.0, 0, []
    off = 0
    while off < len(data):
        key, off = _read_varint(data, off)
        field, wire = key >> 3, key & 7
        if wire == 1:  # 64-bit
            if field == 1:
                (wall_time,) = struct.unpack_from("<d", data, off)
            off += 8
        elif wire == 0:  # varint
            value, off = _read_varint(data, off)
            if field == 2:
                step = value
        elif wire == 5:  # 32-bit
            off += 4
        elif wire == 2:  # length-delimited
            length, off = _read_varint(data, off)
            if field == 5:
                scalars = _decode_summary(data[off : off + length])
            off += length
        else:
            raise ValueError("unsupported wire type %d" % wire)
    return wall_time, step, scalars


def _decode_summary(data):
    scalars = []
    off = 0
    while off < len(data):
        key, off = _read_varint(data, off)
        if key >> 3 == 1 and key & 7 == 2:
            length, off = _read_varint(data, off)
            scalars.append(_decode_value(data[off : off + length]))
            off += length
        else:
            raise ValueError("unexpected Summary field")
    return scalars


def _decode_value(data):
    tag, value = "", 0.0
    off = 0
    while off < len(data):
        key, off = _read_varint(data, off)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            length, off = _read_varint(data, off)
            tag = data[off : off + length].decode("utf-8")
            off += length
        elif field == 2 and wire == 5:
            (value,) = struct.unpack_from("<f", data, off)
            off += 4
        elif wire == 0:
            _, off = _read_varint(data, off)
        elif wire == 2:
            length, off = _read_varint(data, off)
            off += length
        elif wire == 5:
            off += 4
        elif wire == 1:
            off += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
    return tag, value
