"""Daemon-thread escapable calls for wedge-prone device interactions.

A dead accelerator transport (TPU tunnel, gloo peer) can block device
calls forever inside C++ where no Python timeout reaches. This leaf
module (no framework imports — the graft-entry device probe must be
able to use it without dragging in the training stack) provides the
machinery both the elastic trainer (parallel/elastic.py) and
``__graft_entry__``'s probe run their device calls through.
"""


class EscapeTimeout(Exception):
    """:func:`escapable_call` abandoned its device thread (hard timeout
    elapsed or the abort probe signalled)."""


def escapable_call(
    fn,
    timeout=None,
    should_abort=None,
    abort_after=2.0,
    abort_interval=1.0,
    poll=0.05,
):
    """Run a device-touching callable on a sacrificial daemon thread so
    the calling thread can escape a wedged accelerator backend.

    ``fn`` runs on a DAEMON thread (daemon, not an executor:
    concurrent.futures joins its workers at interpreter exit, so one
    abandoned wedged thread would hang the process forever at
    shutdown); the caller polls its result queue and gives up by
    raising :class:`EscapeTimeout` when ``timeout`` seconds elapse or
    ``should_abort()`` returns True (probed every ``abort_interval`` s
    after an initial ``abort_after`` s grace; probe exceptions read as
    "don't abort"). The abandoned thread stays parked in the dead call
    — the process must treat the backend as wedged from then on
    (ElasticDPTrainer sets ``_wedged``; __graft_entry__ falls through
    to its CPU re-exec path).

    Returns ``fn()``'s value; re-raises ``fn``'s exception."""
    import queue as _queue
    import threading as _threading
    import time as _time

    out = _queue.Queue(maxsize=1)

    def runner():
        try:
            out.put((True, fn()))
        except BaseException as e:  # noqa: BLE001 - re-raised below
            out.put((False, e))

    t = _threading.Thread(target=runner, name="edl-device", daemon=True)
    t.start()
    t0 = _time.monotonic()
    last_check = t0
    while True:
        try:
            ok, value = out.get(timeout=poll)
        except _queue.Empty:
            pass
        else:
            if ok:
                return value
            raise value
        now = _time.monotonic()
        if timeout is not None and now - t0 >= timeout:
            raise EscapeTimeout(
                "device call still blocked after %.1fs" % timeout
            )
        if (
            should_abort is not None
            and now - t0 >= abort_after
            and now - last_check >= abort_interval
        ):
            last_check = now
            try:
                moved_on = should_abort()
            except Exception:
                moved_on = False
            if moved_on:
                raise EscapeTimeout("abort probe signalled")
