"""Uniform stderr logging (parity: reference common/log_utils.py:5-30)."""

import logging

_LOGGER_CACHE = {}

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
)


def get_logger(name, level=logging.INFO, handler_stream=None):
    key = (name, level, id(handler_stream))
    if key in _LOGGER_CACHE:
        return _LOGGER_CACHE[key]
    logger = logging.getLogger(name)
    logger.setLevel(level)
    handler = logging.StreamHandler(handler_stream)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.propagate = False
    _LOGGER_CACHE[key] = logger
    return logger


default_logger = get_logger("elasticdl_tpu")
