"""Small filesystem helpers (parity: reference common/file_utils.py:7-17)."""

import os
import shutil


def copy_if_not_exists(src, dst, is_dir=False):
    if os.path.exists(dst):
        return
    if is_dir:
        shutil.copytree(src, dst)
    else:
        os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
        shutil.copy(src, dst)
