"""Background-thread checkpoint writes (orbax-style async saving).

The reference writes every checkpoint synchronously on the master thread
(reference master/checkpoint_service.py:47-72): training stalls for the
full serialize+disk time. On TPU the state lives in HBM, so a save
naturally splits into two phases with very different costs:

1. device->host snapshot — bounded by PCIe/DMA, must happen before the
   next train step because training/step.py *donates* the TrainState
   buffers (the arrays are invalidated the moment the next step is
   dispatched);
2. disk IO — the slow part, with no dependency on device state at all.

``AsyncCheckpointer`` runs phase 2 on a single worker thread: saves stay
ordered (version N hits disk before N+1, ring eviction is serialized),
training only ever blocks for phase 1. Errors from the worker are stored
and re-raised on the training thread at the next ``save``/``wait`` so a
failing disk never fails silently.
"""

import queue
import threading

from elasticdl_tpu.common.log_utils import default_logger as logger


class AsyncCheckpointer:
    """Runs submitted IO jobs on one background thread, in order.

    ``max_pending`` bounds the queue: if disk IO falls behind, ``submit``
    blocks rather than accumulating unbounded host snapshots (each queued
    job pins a full model copy in host memory).
    """

    def __init__(self, max_pending=2, name="async-ckpt"):
        self._queue = queue.Queue(maxsize=max_pending)
        self._error = None
        self._error_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._closed = False
        self._thread.start()

    def _run(self):
        while True:
            job = self._queue.get()
            label = ""
            try:
                if job is None:
                    return
                fn, label = job
                fn()
            except Exception as e:  # noqa: BLE001 - relayed to caller
                logger.error("async checkpoint %s failed: %s", label, e)
                with self._error_lock:
                    if self._error is None:
                        self._error = e
            finally:
                # drop the closure before blocking on the next get():
                # fn pins the snapshot (a full host model copy), which
                # must not sit in RAM for the whole inter-checkpoint
                # window
                job = fn = None
                self._queue.task_done()

    def _raise_pending(self):
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def submit(self, fn, label=""):
        """Enqueue ``fn`` (pure IO, no device access) for the worker.

        Raises any error from a previously submitted job first, so a
        broken checkpoint directory surfaces on the training thread at
        the next checkpoint attempt rather than at job teardown.
        """
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        self._queue.put((fn, label))

    def wait(self):
        """Block until every submitted job finished; re-raise failures.

        Call before restoring from the same directory, at job teardown,
        and before any membership change that might re-run the save path
        for the same version.
        """
        self._queue.join()
        self._raise_pending()

    def close(self):
        """Drain outstanding jobs and stop the worker thread."""
        if self._closed:
            return
        self._queue.join()
        self._closed = True
        self._queue.put(None)
        self._thread.join()
        self._raise_pending()
