"""Volume-string parser.

Parity: reference common/k8s_volume.py:6-45 — parse
``"claim_name=c1,mount_path=/p"`` (or ``host_path=...``) into volume +
mount specs. Returns plain dicts; the k8s client renders them into
V1Volume/V1VolumeMount when the kubernetes package is present.
"""


def parse_volume(volume_str):
    """Volume string -> (volume_dict, mount_dict) or None if empty."""
    if not volume_str:
        return None
    kvs = {}
    for pair in volume_str.split(","):
        key, _, value = pair.partition("=")
        kvs[key.strip()] = value.strip()
    if "mount_path" not in kvs:
        raise ValueError("volume spec %r needs mount_path" % volume_str)
    mount = {"name": "edl-volume", "mount_path": kvs["mount_path"]}
    if "claim_name" in kvs:
        volume = {
            "name": "edl-volume",
            "persistent_volume_claim": {"claim_name": kvs["claim_name"]},
        }
    elif "host_path" in kvs:
        volume = {
            "name": "edl-volume",
            "host_path": {
                "path": kvs["host_path"],
                "type": kvs.get("type", "Directory"),
            },
        }
    else:
        raise ValueError(
            "volume spec %r needs claim_name or host_path" % volume_str
        )
    return volume, mount
