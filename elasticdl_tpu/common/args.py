"""Argument/flag system for all four roles.

Parity: reference common/args.py (643 lines) — shared parameter groups,
role-specific parsers (client/train/evaluate/predict, master, PS, worker),
cross-flag validation (async forces ``grads_to_wait=1``, sync forces
``get_model_steps=1``, args.py:547-556), the ``--envs k=v,...`` parser, and
``build_arguments_from_parsed_result`` which re-serializes parsed args back
into CLI flags so config flows client -> master pod -> worker/PS pods
entirely via argv (args.py:622-643).
"""

import argparse


def warn_accum_unsupported(args, plane="this training plane"):
    """Log when --grad_accum_steps is set on a plane that ignores it.

    Accumulation lives in the jitted steps of both ALLREDUCE planes
    (training/step.py:make_train_step,
    parallel/elastic.py:make_elastic_train_step); the PS grad fn runs
    without it, and silence would let a user believe their activation
    memory was bounded when it was not."""
    if getattr(args, "grad_accum_steps", 1) > 1:
        from elasticdl_tpu.common.log_utils import default_logger

        default_logger.warning(
            "--grad_accum_steps=%d is only honored by the ALLREDUCE "
            "strategy (single- and multi-process); %s runs WITHOUT "
            "gradient accumulation",
            args.grad_accum_steps,
            plane,
        )
    if getattr(args, "remat", ""):
        from elasticdl_tpu.common.log_utils import default_logger

        default_logger.warning(
            "--remat=%s is only honored by the ALLREDUCE strategy; %s "
            "runs WITHOUT activation rematerialization (memory will "
            "NOT be bounded as requested)",
            args.remat,
            plane,
        )


def pos_int(arg):
    res = int(arg)
    if res <= 0:
        raise ValueError("Positive integer argument required. Got %s" % res)
    return res


def non_neg_int(arg):
    res = int(arg)
    if res < 0:
        raise ValueError(
            "Non-negative integer argument required. Got %s" % res
        )
    return res


def parse_envs(arg):
    """Parse ``key1=val1,key2=val2`` into a dict (reference args.py:61-86)."""
    env_dict = {}
    if not arg:
        return env_dict
    for pair in arg.split(","):
        key, _, value = pair.partition("=")
        env_dict[key.strip()] = value.strip()
    return env_dict


def print_args(args, exclude_args=(), groups=None):
    from elasticdl_tpu.common.log_utils import default_logger as logger

    for key, value in sorted(vars(args).items()):
        if key not in exclude_args:
            logger.info("%s = %s", key, value)


# -- shared groups ----------------------------------------------------------


def add_bool_param(parser, name, default, help):
    parser.add_argument(
        name,
        nargs="?",
        const=not default,
        default=default,
        type=lambda x: x.lower() in ["true", "yes", "t", "y"],
        help=help,
    )


def add_common_params(parser):
    """Client-common params (reference args.py:100-209)."""
    add_common_args_between_master_and_worker(parser)
    parser.add_argument(
        "--docker_image_repository",
        default="",
        help="Image repository for the job images",
    )
    parser.add_argument("--image_base", default="", help="Base docker image")
    parser.add_argument("--job_name", help="Job name", required=True)
    parser.add_argument(
        "--master_resource_request",
        default="cpu=0.1,memory=1024Mi",
        help="Master resource request",
    )
    parser.add_argument(
        "--master_resource_limit",
        default="",
        help="Master resource limit; defaults to the request",
    )
    parser.add_argument(
        "--num_workers", type=int, default=0, help="Number of workers"
    )
    parser.add_argument(
        "--num_standby_workers",
        type=non_neg_int,
        default=0,
        help="Pre-warmed spare workers (elastic allreduce): parked "
        "after paying their cold start, promoted on a death so "
        "recovery is membership-only",
    )
    parser.add_argument(
        "--worker_resource_request",
        default="cpu=1,memory=4096Mi",
        help="Worker resource request (a TPU worker requests tpu=N here)",
    )
    parser.add_argument(
        "--worker_resource_limit", default="", help="Worker resource limit"
    )
    parser.add_argument(
        "--master_pod_priority", default="", help="Master pod priority"
    )
    parser.add_argument(
        "--worker_pod_priority", default="", help="Worker pod priority"
    )
    parser.add_argument(
        "--volume",
        default="",
        help='Volume spec, e.g. "claim_name=c1,mount_path=/path1"',
    )
    parser.add_argument(
        "--image_pull_policy",
        default="Always",
        help="Image pull policy of the job pods",
    )
    parser.add_argument(
        "--restart_policy", default="Never", help="Pod restart policy"
    )
    parser.add_argument(
        "--envs",
        default="",
        help="Env vars for the job pods, e.g. 'a=b,c=d'",
    )
    parser.add_argument(
        "--extra_pypi_index", default="", help="Extra pypi index url"
    )
    parser.add_argument(
        "--namespace",
        default="default",
        help="Kubernetes namespace for the job pods",
    )
    parser.add_argument(
        "--num_minibatches_per_task",
        type=pos_int,
        default=2,
        help="Number of minibatches per task",
    )
    parser.add_argument(
        "--cluster_spec",
        default="",
        help="Python module rewriting pod/service specs for private clouds",
    )
    parser.add_argument("--docker_base_url", default="unix://var/run/docker.sock")
    parser.add_argument("--docker_tlscert", default="")
    parser.add_argument("--docker_tlskey", default="")
    parser.add_argument(
        "--num_ps_pods", type=int, default=1, help="Number of PS pods"
    )
    parser.add_argument(
        "--ps_resource_request",
        default="cpu=1,memory=4096Mi",
        help="PS resource request",
    )
    parser.add_argument(
        "--ps_resource_limit", default="", help="PS resource limit"
    )
    parser.add_argument("--ps_pod_priority", default="")


def add_train_params(parser):
    """Training params (reference args.py:212-330)."""
    parser.add_argument(
        "--tensorboard_log_dir",
        default="",
        help="Directory for scalar summaries",
    )
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument(
        "--grads_to_wait",
        type=pos_int,
        default=1,
        help="Gradients to accumulate before a sync update",
    )
    parser.add_argument("--training_data", default="", required=True)
    parser.add_argument("--validation_data", default="")
    parser.add_argument(
        "--evaluation_steps",
        type=non_neg_int,
        default=0,
        help="Evaluate every this many model versions",
    )
    parser.add_argument(
        "--evaluation_start_delay_secs", type=non_neg_int, default=100
    )
    parser.add_argument(
        "--evaluation_throttle_secs", type=non_neg_int, default=0
    )
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument(
        "--keep_checkpoint_max", type=non_neg_int, default=0
    )
    parser.add_argument(
        "--replica_refresh_steps",
        type=non_neg_int,
        default=8,
        help="Sharded elastic jobs: refresh the in-HBM replica of each "
        "rank's table shards every this many versions (bounded-"
        "staleness no-disk recovery); 0 disables the replica plane",
    )
    parser.add_argument("--checkpoint_filename_for_init", default="")
    parser.add_argument(
        "--output", default="", help="Trained-model export path"
    )
    add_bool_param(
        parser,
        "--streaming_tasks",
        False,
        "Treat the training data as an unbounded stream: the task "
        "dispatcher rolls a fresh epoch over the shards whenever the "
        "todo queue drains, ignoring --num_epochs, until the job is "
        "stopped — the train half of the train->export->serve loop "
        "(docs/serving.md)",
    )
    add_bool_param(
        parser,
        "--use_async",
        False,
        "Apply gradients asynchronously (host-PS mode only; the ALLREDUCE "
        "strategy is always synchronous in-step)",
    )
    add_bool_param(
        parser,
        "--lr_staleness_modulation",
        False,
        "Modulate learning rate by 1/staleness in async mode",
    )
    # accepted by the master too so the k8s instance manager's argv
    # relay carries the durability config to every PS pod
    add_ps_snapshot_params(parser)


def add_ps_snapshot_params(parser):
    """PS shard durability flags (docs/ps_recovery.md); shared by the
    PS entry and the master (which relays them to PS pods)."""
    parser.add_argument(
        "--ps_snapshot_versions",
        type=non_neg_int,
        default=0,
        help="Durability cadence (docs/ps_recovery.md): snapshot each "
        "PS shard's dense params + embedding/slot tables every N "
        "optimizer versions, off the apply path, and restore the "
        "newest valid snapshot at (re)boot. 0 (default) disables; "
        "requires --ps_snapshot_dir. A crash rolls the shard back at "
        "most N versions instead of to step-0 init",
    )
    parser.add_argument(
        "--ps_snapshot_dir",
        default="",
        help="Base directory for per-shard snapshot state (the shard "
        "writes under <dir>/ps-<id>/). Must survive the pod relaunch "
        "(a persistent volume on k8s; any local path for the "
        "single-host instance manager)",
    )
    parser.add_argument(
        "--ps_snapshot_keep",
        type=pos_int,
        default=2,
        help="Snapshot ring retention: keep this many published "
        "versions; older ones are evicted only after a newer one "
        "published",
    )
    parser.add_argument(
        "--ps_warm_rows",
        type=non_neg_int,
        default=0,
        help="Tiered store (docs/tiered_store.md): per-table warm-tier "
        "row budget on each PS shard. Rows past the budget spill to "
        "disk segments (coldest first, recently-applied rows pinned) "
        "and promote back on demand, so a table can be far larger "
        "than the shard's memory tier. 0 (default) disables; requires "
        "--ps_spill_dir. Composes with --ps_device (the tier wraps "
        "the arena) and with snapshots (a spill segment IS a snapshot "
        "shard; snapshot/restore round-trips across tier configs)",
    )
    parser.add_argument(
        "--ps_spill_dir",
        default="",
        help="Base directory for tiered-store spill segments (the "
        "shard writes under <dir>/ps-<id>/<table>/). Needs only "
        "shard-lifetime durability — segments are re-attached on "
        "relaunch when present, and a cadence-snapshot restore "
        "supersedes them",
    )
    parser.add_argument(
        "--ps_telemetry_port",
        type=int,
        default=-1,
        help="Serve each PS shard's own metric registry (RPC service "
        "histograms under role=ps, edl_ps_snapshot_age_seconds, ...) "
        "plus /events, /trace, and /healthz at this port — parity "
        "with the master's TelemetryHTTPServer (docs/observability.md)"
        ". 0 = ephemeral (exposed as ParameterServer."
        "ps_telemetry_port); -1 (default) disables. Distinct from the "
        "master's --telemetry_port on purpose: the master relays its "
        "own flags to PS pods, and a shared name would make every "
        "co-located shard fight the master for one port",
    )


def add_evaluate_params(parser):
    parser.add_argument("--validation_data", default="", required=True)
    parser.add_argument("--checkpoint_filename_for_init", required=True)
    parser.add_argument(
        "--evaluation_steps", type=non_neg_int, default=0
    )


def add_predict_params(parser):
    parser.add_argument("--prediction_data", default="", required=True)
    parser.add_argument("--prediction_outputs_processor", default="PredictionOutputsProcessor")
    parser.add_argument("--checkpoint_filename_for_init", required=True)


def add_clean_params(parser):
    parser.add_argument("--docker_image_repository", default="")
    add_bool_param(parser, "--all", False, "Remove all local images")
    parser.add_argument("--docker_base_url", default="unix://var/run/docker.sock")
    parser.add_argument("--docker_tlscert", default="")
    parser.add_argument("--docker_tlskey", default="")


def add_common_args_between_master_and_worker(parser):
    """Shared master/worker params (reference args.py:418-500)."""
    parser.add_argument("--minibatch_size", type=pos_int, required=True)
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument(
        "--log_level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"],
    )
    parser.add_argument("--dataset_fn", default="dataset_fn")
    parser.add_argument("--loss", default="loss")
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--eval_metrics_fn", default="eval_metrics_fn")
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--model_params", default="")
    parser.add_argument(
        "--get_model_steps",
        type=pos_int,
        default=1,
        help="Pull the model every this many steps (SSP local updates)",
    )
    parser.add_argument("--data_reader_params", default="")
    parser.add_argument(
        "--distribution_strategy",
        default="ParameterServerStrategy",
        choices=["ParameterServerStrategy", "AllreduceStrategy", "Local"],
        help="ParameterServerStrategy keeps the reference's host-PS "
        "semantics; AllreduceStrategy is the TPU-native in-step XLA "
        "collective path",
    )
    parser.add_argument(
        "--grad_accum_steps",
        type=pos_int,
        default=1,
        help="Gradient accumulation: split each minibatch into this "
        "many microbatches inside the jitted step (activation memory "
        "drops to one microbatch; one optimizer update per minibatch)",
    )
    parser.add_argument(
        "--remat",
        default="",
        help="Activation rematerialization on the ALLREDUCE planes: "
        "'full' (jax.checkpoint the whole forward) or a "
        "jax.checkpoint_policies name (e.g. "
        "dots_with_no_batch_dims_saveable); trades recompute FLOPs for "
        "HBM so deeper models / longer sequences fit per chip",
    )
    parser.add_argument(
        "--precision_policy",
        default="",
        choices=["", "float32", "mixed_bfloat16", "bfloat16"],
        help="Mixed-precision policy for the train step (default: the "
        "model's own dtype behavior; mixed_bfloat16 = f32 master "
        "weights, bf16 compute — the standard TPU recipe)",
    )
    parser.add_argument(
        "--wire_dtype",
        default="",
        choices=["", "bfloat16"],
        help="Compress f32 model pulls and gradient pushes to this "
        "dtype on the wire (PS-mode hot path); receivers upcast back "
        "to f32 before any optimizer math",
    )
    parser.add_argument(
        "--export_dir",
        default="",
        help="Streaming serving exports (docs/serving.md): the worker "
        "writes a complete export artifact (common/export.py, "
        "MANIFEST.json last) under this directory every "
        "--export_every_versions model versions, for the scorer "
        "fleet's ModelDirectoryWatcher to hot-swap in. Distinct from "
        "--output, the end-of-job SAVE_MODEL export",
    )
    parser.add_argument(
        "--export_every_versions",
        type=non_neg_int,
        default=0,
        help="Export the dense graph every this many model versions "
        "when --export_dir is set; 0 disables the cadence",
    )
    parser.add_argument(
        "--export_keep",
        type=pos_int,
        default=4,
        help="Versioned export artifacts to retain under --export_dir "
        "(oldest pruned after each export; scorers mid-load of a "
        "pruned artifact retry on the next watcher poll)",
    )
    parser.add_argument(
        "--hot_row_cache_rows",
        type=int,
        default=0,
        help="PS mode: keep an LRU of this many recently pulled "
        "embedding rows on the worker, served locally instead of over "
        "gRPC while fresh (0 disables; see docs/sparse_fast_path.md)",
    )
    parser.add_argument(
        "--hot_row_staleness_window",
        type=int,
        default=0,
        help="How many PS model versions a hot-row cache entry may lag "
        "before it is re-pulled; 0 (default) binds it to the SSP "
        "window, --get_model_steps",
    )
    add_bool_param(
        parser,
        "--ps_fanout",
        True,
        "Issue the per-shard RPCs of each logical PS call concurrently "
        "(one round trip per call instead of one per shard); false "
        "restores the serial loop (docs/dense_overlap.md)",
    )
    parser.add_argument(
        "--ps_push_inflight",
        type=non_neg_int,
        default=0,
        help="PS mode: allow this many gradient pushes in flight "
        "behind the compute (1 = double buffering; 0 = synchronous "
        "push). The window drains at every model pull and task "
        "boundary, so staleness stays inside the SSP window "
        "(docs/dense_overlap.md); pair with async PS "
        "(--use_async), where late stale-rejections cannot occur",
    )
    parser.add_argument(
        "--rpc_deadline_s",
        type=float,
        default=60.0,
        help="Deadline in seconds for each PS data-plane RPC: a dead "
        "PS pod fails the call (DEADLINE_EXCEEDED into the worker's "
        "minibatch retry loop) instead of hanging forever. 0 disables. "
        "Control-plane master RPCs are NOT bounded (a worker parked on "
        "get_task must block)",
    )
    parser.add_argument(
        "--rpc_retries",
        type=non_neg_int,
        default=2,
        help="Retries (doubling backoff) for UNAVAILABLE PS data-plane "
        "RPCs — the shape a restarting PS pod presents; deadline "
        "expiry is never retried at this layer",
    )
    parser.add_argument(
        "--ps_shm",
        default="auto",
        choices=["auto", "on", "off"],
        help="Shared-memory payload transport toward PS pods "
        "co-located on this host (docs/wire.md): 'auto' (default) "
        "negotiates per channel at first call and silently keeps the "
        "bytes path cross-host or on attach failure; 'off' never "
        "negotiates",
    )
    parser.add_argument(
        "--ps_shm_slots",
        type=pos_int,
        default=4,
        help="Slots per negotiated shm ring (one ring per PS channel); "
        "calls beyond the pool fall back to the bytes path per call",
    )
    parser.add_argument(
        "--ps_shm_slot_mb",
        type=pos_int,
        default=8,
        help="Slot payload size in MiB: one slot must hold one logical "
        "request or reply (a dense pull partition, a per-shard "
        "gradient push); larger payloads ride the bytes path",
    )
    parser.add_argument(
        "--master_shm",
        default="auto",
        choices=["auto", "on", "off"],
        help="Shared-memory payload path for the master channel's "
        "get_model replies when the master pod is co-located on this "
        "host (docs/wire.md): same negotiation and silent bytes-path "
        "fallback as --ps_shm; only the reply-heavy model pull rides "
        "slots — requests stay on the bytes path",
    )
    parser.add_argument(
        "--embedding_plane",
        default="ps",
        choices=["ps", "hybrid"],
        help="Comm-plane trainer mode (docs/embedding_planes.md): 'ps' "
        "round-trips dense parameters through the PS fleet (the "
        "classic parameter-server loop); 'hybrid' keeps dense "
        "parameters (HBM-plane tables included) in the local/"
        "allreduce world and uses the PS fleet only for PS-plane "
        "embedding tables, with the per-batch pull overlapped behind "
        "the previous batch's compute",
    )
    parser.add_argument(
        "--task_prefetch",
        type=non_neg_int,
        default=1,
        help="Keep this many shard tasks fetched ahead of the one being "
        "consumed: a background fetcher overlaps the master get_task "
        "round trip and the cold first-record read with training on "
        "the current task (docs/input_pipeline.md). 0 restores the "
        "serial fetch-then-read loop",
    )
    parser.add_argument(
        "--task_ack_queue",
        type=non_neg_int,
        default=8,
        help="Queue up to this many completed-task acknowledgments "
        "instead of reporting each on the training hot loop; the queue "
        "drains at every task/eval/checkpoint boundary (and inline on "
        "overflow). Failure acks always flush immediately. 0 restores "
        "synchronous per-task acks",
    )
    add_bool_param(
        parser,
        "--speculative_compile",
        False,
        "Elastic allreduce plane: AOT-compile the train step for likely "
        "next world sizes (current±1 and membership-service hints) on a "
        "background thread during steady-state training, so a resize to "
        "a pre-compiled size pays state re-placement only; pair with "
        "EDL_COMPILE_CACHE_DIR so relaunched processes skip XLA "
        "compiles too (docs/compile_plane.md)",
    )
    parser.add_argument(
        "--telemetry_report_secs",
        type=float,
        default=5.0,
        help="Workers piggyback a compact telemetry snapshot "
        "(step/examples rates, input-plane counters, pending events) "
        "on the master channel at most every this many seconds "
        "(docs/observability.md); 0 disables worker telemetry "
        "reporting. EDL_METRICS=0 disables ALL telemetry recording",
    )
    parser.add_argument(
        "--loss_log_steps",
        type=non_neg_int,
        default=20,
        help="Log the training loss every this many accepted "
        "minibatches; each log costs a device->host sync, so the "
        "per-step logging of the reference is off the hot path. 0 "
        "disables loss logging",
    )
    parser.add_argument(
        "--master_failover_s",
        type=float,
        default=120.0,
        help="Worker-side master failover budget in seconds "
        "(docs/master_recovery.md): UNAVAILABLE master RPCs retry "
        "with capped backoff for up to this long — the window a "
        "SIGKILLed master needs to relaunch and replay its journal — "
        "instead of killing the worker. Task acks replayed against "
        "the new incarnation dedup by (trace_id, attempt). 0 restores "
        "the historical die-on-outage behavior",
    )


def parse_master_args(master_args=None):
    parser = argparse.ArgumentParser(description="ElasticDL TPU Master")
    # port 0 = pick a free port (the chosen one is exposed as Master.port);
    # None = "not set": cluster mode uses 50001, local mode uses 0
    parser.add_argument("--port", type=non_neg_int, default=None)
    parser.add_argument("--worker_image", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument(
        "--prediction_outputs_processor",
        default="PredictionOutputsProcessor",
    )
    parser.add_argument(
        "--telemetry_port",
        type=non_neg_int,
        default=None,
        help="Serve the job telemetry registry as Prometheus text on "
        "http://master:PORT/metrics (plus /events as JSONL); 0 binds "
        "an ephemeral port (exposed as Master.telemetry_port); unset "
        "disables the endpoint (aggregation still runs)",
    )
    parser.add_argument(
        "--telemetry_events_path",
        default="",
        help="Append the master's structured job-event log (resize, "
        "task requeue/timeline, worker join/leave, PS shard failure) "
        "as JSON lines to this file; empty disables the file sink "
        "(the in-memory tail still serves /events)",
    )
    parser.add_argument(
        "--comm_base_port",
        type=non_neg_int,
        default=0,
        help="Allreduce-plane coordinator port base; each membership "
        "epoch binds base+epoch%%64 on rank 0's host. 0 picks ephemeral "
        "ports (single-host jobs)",
    )
    parser.add_argument(
        "--master_journal_dir",
        default="",
        help="Master recovery plane (docs/master_recovery.md): append "
        "a write-ahead journal of task lifecycle transitions, epoch "
        "boundaries, the model-version clock, and membership changes "
        "under this directory; a relaunched master (same args, same "
        "dir) replays it before serving so done tasks stay done and "
        "in-flight tasks requeue exactly once. Empty disables "
        "durability (a master crash kills the job, the historical "
        "behavior)",
    )
    parser.add_argument(
        "--master_journal_fsync_ms",
        type=float,
        default=50.0,
        help="Batched fsync cadence of the journal writer thread: "
        "appends are enqueue-only on the RPC path and at most this "
        "many milliseconds of accepted transitions can be lost to a "
        "hard kill (a lost 'done' re-trains that task; accounting "
        "stays exactly-once either way)",
    )
    parser.add_argument(
        "--master_journal_segment_records",
        type=pos_int,
        default=4096,
        help="Rotate + compact the journal after this many records: a "
        "fresh segment opens with a state snapshot (write-to-temp + "
        "atomic rename, the PR-10 manifest discipline) and the "
        "superseded chain is unlinked, bounding replay time and disk",
    )
    add_common_params(parser)
    add_train_params(parser)
    args, unknown = parser.parse_known_args(args=master_args)
    _validate(args)
    return args


def parse_ps_args(ps_args=None):
    parser = argparse.ArgumentParser(description="ElasticDL TPU PS")
    parser.add_argument("--ps_id", type=non_neg_int, required=True)
    parser.add_argument("--port", type=pos_int, required=True)
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--grads_to_wait", type=pos_int, default=1)
    add_bool_param(parser, "--use_async", False, "")
    add_bool_param(parser, "--lr_staleness_modulation", False, "")
    add_bool_param(
        parser,
        "--ps_device",
        False,
        help="Device-resident shard (docs/ps_device.md): dense params, "
        "embedding tables and optimizer state live as jax.Arrays with "
        "jitted apply paths and compiled embedding gather/scatter; "
        "incoming gradients decode straight to device. Bitwise-"
        "identical to the host shard on every RPC (snapshot format, "
        "delta log and reconnect protocol unchanged). Off (default) "
        "keeps the host-numpy store",
    )
    parser.add_argument(
        "--wire_dtype", default="", choices=["", "bfloat16"]
    )
    parser.add_argument(
        "--rpc_inject_delay_ms",
        type=float,
        default=0.0,
        help="Test/bench fault injection: sleep this long in every RPC "
        "handler before serving it — models cross-pod network RTT on "
        "loopback fleets so overlap benchmarks measure what a real "
        "deployment would see. 0 (default) disables",
    )
    add_ps_snapshot_params(parser)
    parser.add_argument(
        "--log_level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"],
    )
    args, unknown = parser.parse_known_args(args=ps_args)
    return args


def parse_worker_args(worker_args=None):
    parser = argparse.ArgumentParser(description="ElasticDL TPU Worker")
    parser.add_argument("--worker_id", type=int, required=True)
    parser.add_argument("--job_type", required=True)
    parser.add_argument("--master_addr", default="")
    parser.add_argument("--ps_addrs", default="", help="Comma-separated")
    parser.add_argument(
        "--comm_host",
        default="",
        help="Host other allreduce workers can reach this process at "
        "(the coordinator address when it is rank 0); defaults to "
        "$EDL_COMM_HOST or the hostname",
    )
    # sharded (worker-side) checkpointing for the allreduce plane; the
    # master relays its own values for these via the argv relay
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument(
        "--replica_refresh_steps", type=non_neg_int, default=8
    )
    add_bool_param(
        parser,
        "--standby",
        False,
        help="Start as a pre-warmed spare: pay the cold start (jax "
        "import) now, park until the master promotes this process "
        "with a real worker id (elastic allreduce only)",
    )
    parser.add_argument(
        "--checkpoint_filename_for_init",
        default="",
        help="Exported model file evaluation-only allreduce workers "
        "score (relayed from the master's flag of the same name)",
    )
    parser.add_argument(
        "--keep_checkpoint_max", type=non_neg_int, default=0
    )
    parser.add_argument(
        "--prediction_outputs_processor",
        default="PredictionOutputsProcessor",
    )
    add_common_args_between_master_and_worker(parser)
    args, unknown = parser.parse_known_args(args=worker_args)
    return args


def parse_scorer_args(scorer_args=None):
    """The serving plane's scorer process (elasticdl_tpu/serving/main):
    one scorer pod of the fleet answering inference traffic from the
    latest export artifact + PS-resident embeddings (docs/serving.md).
    """
    parser = argparse.ArgumentParser(description="ElasticDL TPU Scorer")
    parser.add_argument("--scorer_id", type=int, default=0)
    parser.add_argument(
        "--export_dir",
        required=True,
        help="Export root the trainer's streaming cadence writes "
        "versioned artifacts under; the scorer watches it and "
        "hot-swaps to the newest MANIFEST.json",
    )
    parser.add_argument(
        "--ps_addrs",
        default="",
        help="Comma-separated PS shard addresses serving the elastic "
        "embedding tables read-through; empty for dense-only models",
    )
    parser.add_argument(
        "--port",
        type=non_neg_int,
        default=0,
        help="Scorer RPC port (0 binds ephemeral)",
    )
    parser.add_argument(
        "--scorer_telemetry_port",
        type=int,
        default=-1,
        help="Serve this scorer's /metrics + /healthz + /events + "
        "/trace on this port (0 = ephemeral, -1 disables) — the "
        "request-latency histogram, staleness gauge, and cache hit "
        "rate the serving gates scrape (docs/serving.md)",
    )
    parser.add_argument(
        "--serving_staleness_versions",
        type=pos_int,
        default=2,
        help="Freshness bound: a served embedding row is never more "
        "than this many shard versions behind the newest version this "
        "scorer has seen — the hot-row cache window, kept cheap by "
        "the delta sync (docs/serving.md)",
    )
    parser.add_argument(
        "--serving_sync_interval_s",
        type=float,
        default=0.5,
        help="Delta-sync poll cadence against each PS shard's "
        "serving_status; backs off with capped doubling while the "
        "fleet is unreachable",
    )
    parser.add_argument(
        "--hot_row_cache_rows",
        type=pos_int,
        default=65536,
        help="Read-through hot-row cache capacity (rows) shared by "
        "the request path and the delta sync",
    )
    parser.add_argument(
        "--watch_interval_s",
        type=float,
        default=1.0,
        help="Export-directory poll cadence for new model versions",
    )
    parser.add_argument(
        "--serve_max_batch",
        type=non_neg_int,
        default=64,
        help="Micro-batching row budget: concurrent score requests "
        "coalesce into one jitted forward against power-of-two "
        "buckets up to this (docs/serving.md, Micro-batching); "
        "0 or 1 disables batching (the pre-PR-18 inline path)",
    )
    parser.add_argument(
        "--serve_batch_timeout_ms",
        type=float,
        default=2.0,
        help="Latency-budget cutoff: a coalesced batch dispatches at "
        "a full bucket or this many ms after its oldest request "
        "enqueued, whichever first — a lone request never waits for "
        "a full bucket",
    )
    parser.add_argument(
        "--serve_p99_slo_ms",
        type=float,
        default=0.0,
        help="SLO admission control: shed (explicit "
        "{'error': 'overloaded'}) when the predicted completion time "
        "— queued batches ahead x the p99 forward estimate from the "
        "request-latency histogram — exceeds this; 0 disables",
    )
    parser.add_argument(
        "--serve_queue_rows",
        type=non_neg_int,
        default=0,
        help="Hard cap on queued rows before shedding queue_full "
        "(0 -> 8 x --serve_max_batch) — bounds memory and tail "
        "latency even before the SLO estimate warms up",
    )
    parser.add_argument(
        "--model_zoo",
        default="",
        help="Override the artifact metadata's model_zoo path when "
        "the trainer's path is not valid on this host",
    )
    parser.add_argument(
        "--rpc_deadline_s",
        type=float,
        default=20.0,
        help="Deadline per PS data-plane RPC on the scorer's pull "
        "path (0 disables)",
    )
    parser.add_argument(
        "--rpc_retries",
        type=non_neg_int,
        default=3,
        help="Bounded UNAVAILABLE retries (doubling backoff) on the "
        "scorer's idempotent pull path — the PR-12 failover posture "
        "scaled to a data plane (docs/serving.md)",
    )
    parser.add_argument(
        "--ps_shm",
        default="auto",
        choices=["auto", "on", "off"],
        help="Shared-memory payload transport toward co-located PS "
        "shards (docs/wire.md), same negotiation/fallback as the "
        "worker's flag",
    )
    parser.add_argument(
        "--log_level",
        default="INFO",
        choices=["DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"],
    )
    args, unknown = parser.parse_known_args(args=scorer_args)
    return args


def _validate(args):
    """Cross-flag validation (reference args.py:547-556)."""
    if getattr(args, "use_async", False) and args.grads_to_wait > 1:
        args.grads_to_wait = 1
        from elasticdl_tpu.common.log_utils import default_logger as logger

        logger.warning(
            "grads_to_wait is forced to 1 for async SGD"
        )
    if not getattr(args, "use_async", False):
        if getattr(args, "get_model_steps", 1) > 1:
            args.get_model_steps = 1
            from elasticdl_tpu.common.log_utils import (
                default_logger as logger,
            )

            logger.warning(
                "get_model_steps is forced to 1 for sync SGD"
            )


def build_arguments_from_parsed_result(args, filter_args=None):
    """Reconstruct CLI flags from parsed args to forward to child pods.

    Reference args.py:622-643 — the master re-serializes its own args into
    the worker/PS command lines, so config flows purely via argv.
    """
    items = vars(args).items()
    if filter_args:
        items = [(k, v) for k, v in items if k not in filter_args]
    arguments = []
    for key, value in items:
        if value is None:
            continue
        if isinstance(value, bool):
            value = "true" if value else "false"
        arguments.extend(["--" + key, str(value)])
    return arguments
