"""Model-zoo module loading and spec resolution.

Parity: reference common/model_utils.py — dynamic import of a model-zoo
module by dotted path (model_utils.py:10-54), resolution of the user
contract ``custom_model/CustomModel``, ``loss``, ``optimizer``,
``dataset_fn``, ``eval_metrics_fn``, ``PredictionOutputsProcessor`` with
cross-module spec keys (model_utils.py:57-135), and checkpoint
save/load (model_utils.py:138-150).

The TPU-native contract differs only in *types*: ``custom_model()`` returns
a flax ``nn.Module`` (not keras), ``optimizer(lr)`` returns an optax
``GradientTransformation``, ``loss(output, labels)`` is jnp, and
``dataset_fn(dataset, mode, metadata)`` receives the framework's tf-free
Dataset shim (elasticdl_tpu/data/dataset.py).
"""

import importlib.util
import json
import os

from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.tensor import Tensor, deserialize_tensors, serialize_tensors


_module_cache = {}  # abspath -> (mtime, module)


def load_module(module_file):
    """Load a zoo module, cached per (path, mtime).

    Several call sites resolve the same module per process (spec
    resolution, strategy-rewrite hooks); re-executing it would repeat
    module-level side effects and hand out distinct class identities.
    """
    path = os.path.abspath(module_file)
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        mtime = None
    cached = _module_cache.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    spec = importlib.util.spec_from_file_location(module_file, module_file)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    _module_cache[path] = (mtime, module)
    return module


def get_module_file_path(model_zoo, spec_key):
    """Dotted spec key -> file path under the model zoo root.

    ``"mnist_functional_api.mnist_functional_api.custom_model"`` maps to
    ``{zoo}/mnist_functional_api/mnist_functional_api.py`` (the last dotted
    element is the symbol, the rest the module path) —
    reference model_utils.py:21-27.
    """
    return os.path.join(model_zoo, *spec_key.split(".")[:-1]) + ".py"


def get_dict_from_params_str(params_str):
    """Parse ``"a=1,b='x'"`` into a kwargs dict (model_utils.py:36-44)."""
    if not params_str:
        return None
    kv = {}
    for kv_str in params_str.split(","):
        k, _, v = kv_str.partition("=")
        try:
            kv[k.strip()] = eval(v)  # noqa: S307 - same trust model as argparse
        except Exception:
            kv[k.strip()] = v
    return kv


def load_model_from_module(model_def, model_def_module, model_params):
    """Instantiate the model: ``custom_model(**params)`` or class ctor.

    Mirrors reference model_utils.py:47-54: if the named symbol is a
    function it is called with model_params kwargs; if it is a class the
    class is instantiated.
    """
    model_def_name = model_def.split(".")[-1]
    if model_def_name not in model_def_module:
        raise ValueError(
            "Cannot find the model definition %s in the module" % model_def
        )
    custom_model = model_def_module[model_def_name]
    kwargs = get_dict_from_params_str(model_params) or {}
    return custom_model(**kwargs)


def _get_spec_value(spec_key, model_zoo, default_module, required=False):
    """Resolve a spec key to a symbol, supporting cross-module dotted keys.

    Single-element keys resolve in the model-def module; dotted keys load
    their own module (reference model_utils.py:57-86).
    """
    spec_key_items = spec_key.split(".")
    spec_key_base = spec_key_items[-1]
    if len(spec_key_items) == 1:
        spec_key_module = default_module
    else:
        spec_key_module = load_module(
            get_module_file_path(model_zoo, spec_key)
        ).__dict__
    spec_value = spec_key_module.get(spec_key_base)
    if required and spec_value is None:
        raise ValueError(
            "Missing required spec key %s in the module: %s"
            % (spec_key_base, spec_key)
        )
    return spec_value


class ModelSpec:
    """The resolved user contract for one job."""

    def __init__(
        self,
        model,
        dataset_fn,
        loss,
        optimizer,
        eval_metrics_fn,
        prediction_outputs_processor,
    ):
        self.model = model
        self.dataset_fn = dataset_fn
        self.loss = loss
        self.optimizer = optimizer
        self.eval_metrics_fn = eval_metrics_fn
        self.prediction_outputs_processor = prediction_outputs_processor


def get_model_spec(
    model_zoo,
    model_def,
    model_params=None,
    dataset_fn="dataset_fn",
    loss="loss",
    optimizer="optimizer",
    eval_metrics_fn="eval_metrics_fn",
    prediction_outputs_processor="PredictionOutputsProcessor",
):
    """Resolve the full model spec (reference model_utils.py:89-135)."""
    from elasticdl_tpu.worker.prediction_outputs_processor import (
        BasePredictionOutputsProcessor,
    )

    model_def_module_file = get_module_file_path(model_zoo, model_def)
    default_module = load_module(model_def_module_file).__dict__
    model = load_model_from_module(model_def, default_module, model_params)
    pop = _get_spec_value(
        prediction_outputs_processor, model_zoo, default_module
    )
    if pop is not None and not isinstance(pop, type):
        # allow either a class or an instance in the zoo module
        instance = pop
    elif pop is not None:
        instance = pop()
    else:
        instance = None
    if instance is not None and not isinstance(
        instance, BasePredictionOutputsProcessor
    ):
        logger.warning(
            "prediction_outputs_processor is not inherited from "
            "BasePredictionOutputsProcessor. Prediction outputs may not "
            "be processed correctly."
        )
    return ModelSpec(
        model=model,
        dataset_fn=_get_spec_value(
            dataset_fn, model_zoo, default_module, required=True
        ),
        loss=_get_spec_value(loss, model_zoo, default_module, required=True),
        optimizer=_get_spec_value(
            optimizer, model_zoo, default_module, required=True
        ),
        eval_metrics_fn=_get_spec_value(
            eval_metrics_fn, model_zoo, default_module, required=True
        ),
        prediction_outputs_processor=instance,
    )


# ---------------------------------------------------------------------------
# Checkpoint file codec: {version, named arrays} <-> one .chkpt file.
# Replaces the reference's protobuf Model message (model_utils.py:138-150,
# checkpoint_service.py) with the framework tensor-frame codec.
# ---------------------------------------------------------------------------

import struct

_CKPT_MAGIC = b"EDLC"


def save_checkpoint_to_file(named_arrays, version, file_path):
    payload = serialize_tensors(
        Tensor(name, values) for name, values in sorted(named_arrays.items())
    )
    with open(file_path, "wb") as f:
        f.write(_CKPT_MAGIC)
        f.write(struct.pack("<q", int(version)))
        f.write(payload)


def load_from_checkpoint_file(file_path):
    """Returns (version, {name: ndarray}).

    Also accepts a standard export-artifact directory (common/export.py):
    its ``legacy_checkpoint`` member is this same codec, so every
    init-from-checkpoint surface loads exports with no extra flag."""
    if os.path.isdir(file_path):
        # the member name comes from the artifact's own manifest when
        # present (the export contract, common/export.py) so this
        # resolver follows any relocation instead of hardcoding it
        from elasticdl_tpu.common import export as export_mod

        member = export_mod._LEGACY_CHKPT
        try:
            with open(
                os.path.join(file_path, export_mod.MANIFEST_NAME)
            ) as f:
                member = (
                    json.load(f)["artifacts"].get(
                        "legacy_checkpoint"
                    )
                    or member
                )
        except (OSError, ValueError, KeyError):
            pass
        candidate = os.path.join(file_path, member)
        if not os.path.exists(candidate):
            raise ValueError(
                "%s is a directory without a %s member (not an "
                "elasticdl_tpu export artifact)" % (file_path, member)
            )
        file_path = candidate
    with open(file_path, "rb") as f:
        data = f.read()
    if data[:4] != _CKPT_MAGIC:
        raise ValueError("not an elasticdl_tpu checkpoint: %s" % file_path)
    (version,) = struct.unpack_from("<q", data, 4)
    tensors = deserialize_tensors(memoryview(data)[12:])
    return version, {t.name: t.values for t in tensors}
