"""Named-tensor wire codec — single-copy by contract (docs/wire.md).

Parity: reference common/tensor.py — an ElasticDL ``Tensor`` is a named
ndarray with optional ``indices`` (an IndexedSlices analog for sparse
embedding gradients). The reference serializes to a protobuf message with a
raw ``tobytes()`` payload (tensor.py:110-153). Here the codec is a
self-contained binary frame (JSON header + raw little-endian buffers) so the
control plane / checkpoint layer needs no protoc codegen; the ALLREDUCE data
plane never touches this codec (dense tensors stay in HBM, exchanged by XLA
collectives).

Copy discipline (edlint R10): encoding plans the exact frame size up
front and writes headers + payloads straight into one preallocated
buffer through memoryview slices — one memcpy per payload, with any
bf16 wire downcast (``Tensor.wire_dtype`` mark, set by
rpc/wire_compression) FUSED into that same write via ``np.copyto``.
``jax.Array`` payloads ride the same planner WITHOUT a host-staging
materialization (the dlpack bridge): the plan reads only aval metadata
(shape/dtype/size), and the frame write copies out of the device
buffer through its dlpack/``__array_interface__`` view — on a CPU
backend that view is zero-copy, so the frame write IS the single host
copy; elsewhere it is the one D2H transfer, still fused with any bf16
downcast. Wire-bound device trees therefore skip the
``get_host_state``-style owned-copy staging entirely.
Decoding returns READ-ONLY ``np.frombuffer`` views pinned to the
received buffer; nothing is copied until a consumer that retains or
mutates calls :meth:`Tensor.materialize` (the audited escape hatch).
:class:`WireArena` is the lifetime handle for the backing buffer —
advisory for refcounted ``bytes``, load-bearing for shared-memory
slots (rpc/shm_transport.py), where ``release()`` recycles the slot.

Also provides pytree <-> named-tensor-list bridges so JAX parameter pytrees
can ride the same wire/checkpoint format.
"""

import json
import struct

import numpy as np

from elasticdl_tpu.common.dtypes import (
    dtype_name_to_numpy,
    dtype_numpy_to_name,
)

_MAGIC = b"EDLT"
_VERSION = 1


def is_device_array(x):
    """True for a ``jax.Array`` (duck-typed — no jax import at module
    load): the wire planner treats these as framable payloads whose
    host copy is deferred into the frame write (the dlpack bridge)."""
    return hasattr(x, "aval") and hasattr(x, "__dlpack__")


def _shard_covers_all(index, shape):
    """True when one shard's index tuple spans the whole array."""
    if len(index) != len(shape):
        return False
    return all(
        (s.start or 0) == 0
        and (s.stop is None or s.stop >= dim)
        and (s.step is None or s.step == 1)
        for s, dim in zip(index, shape)
    )


def device_host_view(arr):
    """A host-side numpy view of a device array's buffer — the dlpack
    bridge's copy source.

    A fully-replicated array (per jax's own metadata — never inferred
    from local shard indices, which lie in multi-process topologies)
    or a single shard spanning the whole array exports that one device
    buffer through dlpack / ``__array_interface__`` — zero-copy on a
    CPU backend, the single D2H transfer elsewhere. Anything else
    falls back to ``jax.device_get``, which assembles fully-addressable
    sharded arrays — the one materialization dlpack cannot express
    (edlint R10 ratchet) — and raises jax's own clear error for an
    array this process cannot see all of (framing one is a caller
    bug: the frame needs every byte). The returned view is read-only
    where zero-copy; callers only ever ``np.copyto`` FROM it."""
    shards = getattr(arr, "addressable_shards", None)
    src = None
    if shards:
        if getattr(arr, "is_fully_replicated", False):
            # every shard holds the whole value; any local one serves
            src = shards[0].data
        elif len(shards) == 1 and _shard_covers_all(
            shards[0].index, arr.shape
        ):
            src = shards[0].data
    if src is not None:
        try:
            return np.from_dlpack(src)
        except (BufferError, RuntimeError, TypeError, ValueError):
            # cross-device dlpack (a TPU/GPU buffer numpy cannot
            # view); device_get below is then the one staged D2H
            pass
    import jax

    return jax.device_get(arr)


def device_from_host_view(arr):
    """The inverse bridge: a device array over a host buffer — how a
    decoded wire view enters a device-resident PS shard
    (ps/device_store.py, docs/ps_device.md).

    A writable float32 numpy view imports through dlpack with NO copy:
    the returned ``jax.Array`` ALIASES the host buffer, so on a CPU
    backend a shm-slot gradient flows slot -> dlpack view -> jitted
    apply with zero host staging. The caller owns the lifetime
    contract — it must ``jax.block_until_ready`` everything computed
    from the import before the backing buffer is recycled (the shm
    server overwrites the request slot with the reply the moment the
    handler returns), and must never donate the aliased array.

    Read-only views (numpy cannot export them pre-DLPack-1.0) and
    non-f32/non-contiguous payloads fall back to ``jax.device_put`` —
    one fused H2D copy, the exact dual of :func:`device_host_view`'s
    ``device_get`` fallback. Device arrays pass through untouched."""
    if is_device_array(arr):
        return arr
    import jax

    flags = getattr(arr, "flags", None)
    if (
        flags is not None
        and flags.writeable
        and flags.c_contiguous
        and arr.dtype == np.float32
    ):
        try:
            return jax.dlpack.from_dlpack(arr)
        except (BufferError, RuntimeError, TypeError, ValueError):
            pass  # backend refused the import; device_put below
    return jax.device_put(arr)


class Tensor:
    """A named ndarray, optionally sparse (values + row indices).

    Mirrors reference common/tensor.py:17-107. ``indices`` non-None means
    the tensor is an IndexedSlices analog: ``values[i]`` is the row update
    for row ``indices[i]`` of the named parameter.
    """

    def __init__(self, name=None, values=None, indices=None):
        self.name = name
        if values is None or is_device_array(values):
            # device arrays stay device arrays: the frame planner reads
            # only their aval metadata, and the single host copy happens
            # inside the frame write (dlpack bridge) — an np.asarray
            # here would be the host-staging pass the bridge removes
            self.values = values
        else:
            self.values = np.asarray(values)
        self.indices = (
            None if indices is None else np.asarray(indices, dtype=np.int64)
        )
        # wire downcast mark (rpc/wire_compression.compress_tensors):
        # a numpy dtype the f32 payload narrows to DURING the frame
        # copy-out, so compression costs no separate allocation pass.
        # Metadata only — ``values`` itself is never converted here.
        self.wire_dtype = None
        if self.indices is not None and self.values is not None:
            if len(self.indices) != self.values.shape[0]:
                raise ValueError(
                    "indices length %d != values rows %d"
                    % (len(self.indices), self.values.shape[0])
                )

    def is_indexed_slices(self):
        return self.indices is not None

    def __add__(self, other):
        """Sparse tensors concatenate; dense tensors add elementwise.

        Mirrors reference tensor.py:92-104 (used for sync gradient
        accumulation; duplicate sparse indices are resolved at apply time).
        """
        if not isinstance(other, Tensor):
            if other == 0:  # support sum(tensors)
                return self
            return NotImplemented
        if self.is_indexed_slices() != other.is_indexed_slices():
            raise ValueError("cannot add sparse and dense tensors")
        if self.is_indexed_slices():
            return Tensor(
                self.name,
                np.concatenate([self.values, other.values], axis=0),
                np.concatenate([self.indices, other.indices], axis=0),
            )
        return Tensor(self.name, self.values + other.values)

    __radd__ = __add__

    def combined(self):
        """Row-combined copy of a sparse tensor (dense: self).

        Duplicate ``indices`` are merged by summing their rows — the
        resolution ``__add__``'s concatenation defers to apply time,
        done eagerly. Pushing ``t.combined()`` instead of ``t`` puts
        one row per unique id on the wire with identical training
        semantics (the PS applies the sum either way)."""
        if not self.is_indexed_slices():
            return self
        indices, values = combine_indexed_slices(self.indices, self.values)
        return Tensor(self.name, values, indices=indices)

    def materialize(self):
        """An owned, writable twin of a zero-copy decoded tensor.

        Decoded payloads are read-only views pinned to the wire buffer
        (docs/wire.md); a consumer that RETAINS a tensor past its
        message's arena lifetime, or needs in-place math, must go
        through here first. Tensors whose payloads are already writable
        (locally constructed, or already materialized) return ``self``
        unchanged, so the call is free everywhere but the decode edge.
        """
        # device arrays count as owned: they are immutable device
        # buffers, not views pinned to a wire arena
        v_flags = getattr(self.values, "flags", None)
        v_owned = v_flags is None or v_flags.writeable
        i_owned = self.indices is None or self.indices.flags.writeable
        if v_owned and i_owned:
            return self
        return Tensor(
            self.name,
            self.values if v_owned else self.values.copy(),
            self.indices if i_owned else self.indices.copy(),
        )

    def to_bytes(self):
        return serialize_tensor(self)

    @classmethod
    def from_bytes(cls, data):
        return deserialize_tensor(data)


def combine_indexed_slices(indices, values):
    """Segment-sum duplicate rows: returns (unique_indices, summed_values).

    The sparse-comms row-combine both embedding planes share
    (nn/sparse_comms.py): the worker runs it before any gradient push so
    the wire carries one row per unique id, and the PS runs it before
    any optimizer apply (ps/optimizer_wrapper.py delegates here).
    ``unique_indices`` comes back sorted (np.unique order)."""
    indices = np.asarray(indices, dtype=np.int64)
    values = np.asarray(values, dtype=np.float32)
    unique, inverse = np.unique(indices, return_inverse=True)
    if len(unique) == len(indices):
        # already duplicate-free: skip the scatter (hot path when the
        # lookup plan deduped before the pull)
        order = np.argsort(indices, kind="stable")
        return unique, values[order]
    combined = np.zeros((len(unique), values.shape[1]), dtype=np.float32)
    np.add.at(combined, inverse, values)
    return unique, combined


_FIXED = 9  # magic(4) + version(1) + header_len(4)
_INT64 = np.dtype(np.int64)


def plan_tensor_frame(t):
    """Exact layout of one tensor frame, computed WITHOUT touching the
    payload: ``(header_bytes, values, wire_np_dtype, indices, total)``.

    The plan is what scatter-gather writers consume
    (:func:`write_tensor_frame`, rpc/core's message packer): the total
    lets the caller preallocate one buffer for any number of frames,
    and the wire dtype carries the fused bf16 downcast decision — a
    marked f32 payload serializes narrow without an intermediate
    ``astype`` array ever existing. ``jax.Array`` values plan from
    aval metadata alone (shape/dtype/size — no device interaction);
    their single host copy happens inside :func:`write_tensor_frame`.
    """
    values = t.values
    wire = t.wire_dtype if getattr(t, "wire_dtype", None) is not None else None
    out_dtype = (
        wire
        if wire is not None and values.dtype == np.float32
        else values.dtype
    )
    header = {
        "name": t.name,
        "dtype": dtype_numpy_to_name(out_dtype),
        "shape": list(values.shape),
    }
    indices = t.indices
    if indices is not None:
        header["num_indices"] = int(indices.shape[0])
    hdr = json.dumps(header).encode("utf-8")
    total = _FIXED + len(hdr) + values.size * out_dtype.itemsize
    if indices is not None:
        total += indices.shape[0] * 8
    return hdr, values, out_dtype, indices, total


def _write_array(buf, off, arr, dtype):
    """ONE memcpy of ``arr`` into ``buf[off:]`` as C-order ``dtype``.

    ``np.copyto`` handles strided sources (so no ``ascontiguousarray``
    staging copy) and fuses any dtype narrowing (f32 -> bf16 wire
    compression) into the same pass. Device arrays copy out of their
    dlpack/``__array_interface__`` view — the frame write is their one
    host copy, downcast included. Returns the new offset."""
    nbytes = arr.size * dtype.itemsize
    if nbytes:
        if is_device_array(arr):
            arr = device_host_view(arr)
        dest = np.frombuffer(buf[off : off + nbytes], dtype=dtype)
        np.copyto(dest.reshape(arr.shape), arr, casting="unsafe")
    return off + nbytes


def write_tensor_frame(plan, buf, off=0):
    """Write one planned frame into ``buf`` (a writable memoryview /
    bytearray) at ``off``; returns the offset past the frame."""
    if not isinstance(buf, memoryview):
        # a bytearray SLICE copies; all writes must go through one view
        buf = memoryview(buf)
    hdr, values, out_dtype, indices, _total = plan
    struct.pack_into("<4sBI", buf, off, _MAGIC, _VERSION, len(hdr))
    off += _FIXED
    buf[off : off + len(hdr)] = hdr
    off += len(hdr)
    off = _write_array(buf, off, values, out_dtype)
    if indices is not None:
        off = _write_array(buf, off, indices, _INT64)
    return off


def serialize_tensor(t):
    """Frame: magic | u8 ver | u32 header_len | header json | values | indices.

    Header carries name/dtype/shape (+ indices count); payloads are raw
    C-order little-endian buffers written straight into the one exact
    preallocation — a single memcpy per payload, the bf16 wire downcast
    fused in when ``t.wire_dtype`` is set. Returns a ``bytearray``
    (bytes-like); the frame bytes are identical to the historical
    join-based codec, so mixed-version fleets interoperate.
    """
    plan = plan_tensor_frame(t)
    buf = bytearray(plan[4])
    write_tensor_frame(plan, buf)
    return buf


def _readonly(data):
    """A read-only memoryview of ``data`` — the writable=False floor
    every decoded view inherits (numpy propagates the flag)."""
    view = data if isinstance(data, memoryview) else memoryview(data)
    return view if view.readonly else view.toreadonly()


def deserialize_tensor(data, writable=False):
    """Zero-copy decode: values and indices come back as READ-ONLY
    ``np.frombuffer`` views pinned to ``data`` (the views hold the
    buffer alive; see :class:`WireArena` for the explicit lifetime
    handle). Mutating/retaining consumers call
    :meth:`Tensor.materialize` — in-process fast paths (the master
    rung, tests) read straight out of the frame buffer with no copy at
    all, indices included.

    ``writable=True`` (device-resident PS shards only) keeps the views
    writable when ``data`` itself is — numpy refuses to dlpack-export
    a read-only buffer, so this is what lets a shm-slot payload enter
    the device with zero copies (:func:`device_from_host_view`). It
    FORFEITS :meth:`Tensor.materialize`'s view detection (a writable
    view looks owned), so every consumer on that path must copy
    explicitly if it retains — the device apply paths consume within
    the handler instead."""
    view = (
        _readonly(data)
        if not writable
        else (data if isinstance(data, memoryview) else memoryview(data))
    )
    if view[:4] != _MAGIC:
        raise ValueError("bad tensor frame magic")
    ver, hlen = struct.unpack_from("<BI", view, 4)
    if ver != _VERSION:
        raise ValueError("unsupported tensor frame version %d" % ver)
    off = _FIXED
    header = json.loads(bytes(view[off : off + hlen]))
    off += hlen
    dtype = dtype_name_to_numpy(header["dtype"])
    shape = tuple(header["shape"])
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    values = np.frombuffer(view[off : off + nbytes], dtype=dtype).reshape(
        shape
    )
    off += nbytes
    indices = None
    if "num_indices" in header:
        n = header["num_indices"]
        indices = np.frombuffer(view[off : off + 8 * n], dtype=np.int64)
    return Tensor(header["name"], values, indices)


def serialize_tensors(tensors):
    """Concatenate framed tensors with a u64 length prefix each —
    planned once, written into ONE exact preallocation (the historical
    per-frame join plus outer join both folded away)."""
    plans = [plan_tensor_frame(t) for t in tensors]
    buf = bytearray(sum(8 + p[4] for p in plans))
    off = 0
    for plan in plans:
        struct.pack_into("<Q", buf, off, plan[4])
        off = write_tensor_frame(plan, buf, off + 8)
    return buf


def deserialize_tensors(data):
    view = _readonly(data)
    off = 0
    tensors = []
    while off < len(view):
        (n,) = struct.unpack_from("<Q", view, off)
        off += 8
        tensors.append(deserialize_tensor(view[off : off + n]))
        off += n
    return tensors


class WireArena:
    """Lifetime handle for the buffer backing zero-copy decoded views.

    On the gRPC bytes path the decoded numpy views refcount the buffer
    themselves, so ``release()`` is advisory — views created from OTHER
    messages (or this one) stay valid after it. On the shared-memory
    path (rpc/shm_transport.py) ``release()`` RECYCLES the slot: views
    into it become invalid, which is why the audited retention sites
    materialize before their message is released. ``__del__`` is the
    backstop so a dropped reply can never leak a slot."""

    __slots__ = ("_buf", "_on_release", "released")

    def __init__(self, buf, on_release=None):
        self._buf = buf
        self._on_release = on_release
        self.released = False

    @property
    def recycles(self):
        """True when release() actually invalidates the views (a shm
        slot arena): consumers that retain decoded tensors must
        materialize first. False on the advisory gRPC-bytes arena,
        where retained views stay valid — callers can keep the
        zero-copy fast path there."""
        return self._on_release is not None and not self.released

    def release(self):
        if self.released:
            return
        self.released = True
        self._buf = None
        callback, self._on_release = self._on_release, None
        if callback is not None:
            callback()

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter-teardown destructor
            pass


def release_message(msg):
    """Release the arena pinning a decoded message's buffer (no-op for
    messages that carry none — in-process dicts, handler-side requests).
    After this, tensors decoded from a shared-memory reply are invalid;
    anything retained must have been materialized first."""
    if isinstance(msg, dict):
        arena = msg.pop("_wire_arena", None)
        if arena is not None:
            arena.release()


# ---------------------------------------------------------------------------
# pytree bridges: JAX parameter pytrees <-> flat {name: ndarray} dicts.
# The wire/checkpoint name of a leaf is its joined key path ("dense/kernel"),
# which plays the role of the reference's TF variable names.
# ---------------------------------------------------------------------------


def _join_path(path):
    import jax.tree_util as jtu

    parts = []
    for p in path:
        if isinstance(p, jtu.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jtu.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jtu.GetAttrKey):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def pytree_to_named_arrays(tree, keep_device=False):
    """Flatten a pytree of arrays into an ordered {path_name: array}.

    ``keep_device=True`` leaves ``jax.Array`` leaves on device for a
    WIRE-BOUND tree (gradient pushes, model pushes): the frame writer
    copies straight out of the device buffer (dlpack bridge), so the
    np.asarray host staging here would be a wasted full-payload pass.
    Default (False) materializes host numpy — the checkpoint/export
    contract, where callers index and retain the arrays."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    if keep_device:
        return {
            _join_path(path): (
                leaf if is_device_array(leaf) else np.asarray(leaf)
            )
            for path, leaf in flat
        }
    return {_join_path(path): np.asarray(leaf) for path, leaf in flat}


def named_arrays_to_nested(named):
    """Nest {path_name: value} back into plain dicts by the "/" path
    convention of :func:`pytree_to_named_arrays` (the structure-free
    inverse — use :func:`named_arrays_to_pytree` when a template
    pytree is available)."""
    tree = {}
    for name, value in named.items():
        node = tree
        parts = name.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def named_arrays_to_pytree(named, like):
    """Unflatten {path_name: ndarray} back into the structure of ``like``."""
    import jax

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, leaf in paths_and_leaves:
        name = _join_path(path)
        if name not in named:
            raise KeyError("missing tensor %r for pytree restore" % name)
        arr = np.asarray(named[name])
        if hasattr(leaf, "shape") and tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                "shape mismatch for %r: %s vs %s"
                % (name, arr.shape, leaf.shape)
            )
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)
